"""Fault-injection layer + supervised recovery (PERF.md §23).

The fault matrix: for every named injection point the fault is armed
and the DOCUMENTED recovery asserted — retry succeeds with byte-exact
hit-stream parity, a failing packed group demotes to solo machines, a
dead worker's executor restarts once, a corrupt checkpoint fails with
the typed error — plus the spec-grammar/determinism unit tests and the
SIGKILL crash-recovery soak (slow tier: kill ``a5gen serve`` mid-sweep
at a fault-chosen boundary, restart, resubmit from the on-disk
checkpoint, byte parity vs an uninterrupted run).

Tier-1 budget: fast tests share the suite's 64-lane × 16-block
geometry (one compiled program serves them all via the process step
cache); the subprocess soak is slow-marked per the 870 s contract.
"""

import hashlib
import io
import json
import os
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import faults, telemetry
from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
    CheckpointCorrupt,
    atomic_write_text,
    check_bucket_manifest,
    load_checkpoint,
    save_bucket_manifest,
    state_to_doc,
)
from hashcat_a5_table_generator_tpu.runtime.engine import (
    Engine,
    JobFailed,
    serve_socket,
    serve_stdio,
)
from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig
from tests.test_superstep import LEET, WORDS, oracle_lines

LONG_WORDS = WORDS * 4  # spans ~12 supersteps at the 64-lane superstep=1


def cfg(**kw):
    kw.setdefault("superstep", 1)
    return SweepConfig(lanes=64, num_blocks=16, **kw)


def planted_digests(spec, words, picks=(0, 5, 200, -1), decoys=8):
    oracle = oracle_lines(spec, LEET, words)
    digests = sorted({hashlib.md5(oracle[i]).digest() for i in picks})
    digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(decoys)]
    return digests


def full_hits(res):
    return [
        (h.word_index, h.variant_rank, h.candidate, h.digest_hex)
        for h in res.hits
    ]


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def spec():
    return AttackSpec(mode="default", algo="md5")


@pytest.fixture(scope="module")
def digests(spec):
    return planted_digests(spec, LONG_WORDS)


@pytest.fixture(scope="module")
def baseline(spec, digests):
    """The unfaulted run every matrix entry compares against (module-
    scoped: one compile serves the whole file)."""
    return Sweep(spec, LEET, LONG_WORDS, digests, config=cfg()).run_crack()


# ---------------------------------------------------------------------------
# FaultPlan unit tests (no jax)
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_nth_one_shot(self):
        plan = faults.parse_plan("superstep.dispatch:nth=3")
        for i in range(1, 6):
            if i == 3:
                with pytest.raises(faults.FaultInjected):
                    plan.fire("superstep.dispatch")
            else:
                plan.fire("superstep.dispatch")
        assert plan.fired == [("superstep.dispatch", 3)]
        assert plan.calls("superstep.dispatch") == 5

    def test_persist_keeps_firing(self):
        plan = faults.parse_plan("packed.pump:nth=2,persist")
        plan.fire("packed.pump")
        for _ in range(3):
            with pytest.raises(faults.FaultInjected):
                plan.fire("packed.pump")
        assert len(plan.fired) == 3

    def test_probability_is_seed_deterministic(self):
        def pattern(seed):
            plan = faults.parse_plan(
                f"serve.client:p=0.5,seed={seed},persist"
            )
            out = []
            for _ in range(32):
                try:
                    plan.fire("serve.client")
                    out.append(0)
                except faults.FaultInjected:
                    out.append(1)
            return out

        a, b = pattern(7), pattern(7)
        assert a == b
        assert 0 < sum(a) < 32
        assert pattern(8) != a  # a different seed moves the pattern

    def test_error_vocabulary(self):
        plan = faults.parse_plan(
            "superstep.fetch:error=FetchTimeout;"
            "admission.build:error=WorkerDeath"
        )
        with pytest.raises(faults.FetchTimeout):
            plan.fire("superstep.fetch")
        with pytest.raises(faults.WorkerDeath):
            plan.fire("admission.build")
        # WorkerDeath escapes the job-scoped Exception nets by design.
        assert not issubclass(faults.WorkerDeath, Exception)

    def test_points_are_independent(self):
        plan = faults.parse_plan("superstep.dispatch:nth=1")
        plan.fire("superstep.fetch")  # different point: no fire
        with pytest.raises(faults.FaultInjected):
            plan.fire("superstep.dispatch")

    def test_unknown_point_and_options_fail_loudly(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.parse_plan("superstep.dsipatch:nth=1")
        with pytest.raises(ValueError, match="unknown fault error"):
            faults.parse_plan("superstep.fetch:error=Nope")
        with pytest.raises(ValueError, match="unknown fault option"):
            faults.parse_plan("superstep.fetch:bogus=1")
        with pytest.raises(ValueError, match="nth= OR p="):
            faults.parse_plan("superstep.fetch:nth=1,p=0.5")
        with pytest.raises(ValueError, match="no injection points"):
            faults.parse_plan(" ; ")

    def test_armed_restores_previous_plan(self):
        outer = faults.install("serve.client:nth=1")
        with faults.armed("device.init:nth=1") as inner:
            assert faults.ACTIVE is inner
        assert faults.ACTIVE is outer
        faults.clear()
        assert faults.ACTIVE is None

    def test_env_arming_follows_the_variable(self, monkeypatch):
        monkeypatch.setenv("A5GEN_FAULTS", "device.init:nth=1")
        faults.ensure_env()
        assert faults.ACTIVE is not None
        assert faults.ACTIVE.rules[0].point == "device.init"
        monkeypatch.setenv("A5GEN_FAULTS", "")
        faults.ensure_env()
        assert faults.ACTIVE is None

    def test_transient_classification(self):
        assert faults.is_transient(faults.FaultInjected("x"))
        assert faults.is_transient(faults.FetchTimeout("x"))
        assert not faults.is_transient(ValueError("x"))

        class XlaRuntimeError(RuntimeError):
            pass

        assert faults.is_transient(XlaRuntimeError("device lost"))


class TestFetchWatchdog:
    def test_unready_value_times_out_typed(self):
        sweep = SimpleNamespace(config=cfg(fetch_timeout_s=0.05))
        stuck = SimpleNamespace(is_ready=lambda: False)
        with pytest.raises(faults.FetchTimeout, match="fetch_timeout_s"):
            Sweep._await_fetch(sweep, stuck)

    def test_ready_value_passes_and_off_is_noop(self):
        sweep = SimpleNamespace(config=cfg(fetch_timeout_s=0.05))
        Sweep._await_fetch(sweep, SimpleNamespace(is_ready=lambda: True))
        # Watchdog off (default): even a stuck probe is never polled.
        off = SimpleNamespace(config=cfg())
        Sweep._await_fetch(off, SimpleNamespace(is_ready=lambda: False))
        # No readiness probe (plain numpy): falls through to the fetch.
        Sweep._await_fetch(sweep, object())


# ---------------------------------------------------------------------------
# Fault matrix: superstep drive (dispatch / fetch)
# ---------------------------------------------------------------------------


class TestDriveSupervision:
    def test_dispatch_fault_retries_with_parity(self, spec, digests,
                                                baseline):
        with faults.armed("superstep.dispatch:nth=3") as plan:
            got = Sweep(
                spec, LEET, LONG_WORDS, digests, config=cfg()
            ).run_crack()
        assert plan.fired == [("superstep.dispatch", 3)]
        assert full_hits(got) == full_hits(baseline)
        assert got.n_emitted == baseline.n_emitted
        assert got.superstep["retries"] == 1
        assert got.superstep["supersteps"] == baseline.superstep[
            "supersteps"
        ]

    def test_fetch_timeout_fault_retries_with_parity(self, spec, digests,
                                                     baseline):
        before = telemetry.counter("faults.retries").value
        with faults.armed("superstep.fetch:error=FetchTimeout,nth=2"):
            got = Sweep(
                spec, LEET, LONG_WORDS, digests, config=cfg()
            ).run_crack()
        assert full_hits(got) == full_hits(baseline)
        assert got.n_emitted == baseline.n_emitted
        assert telemetry.counter("faults.retries").value == before + 1

    def test_persistent_fault_exhausts_attempts_and_raises(self, spec,
                                                           digests):
        with faults.armed("superstep.dispatch:persist"):
            with pytest.raises(faults.FaultInjected):
                Sweep(
                    spec, LEET, LONG_WORDS, digests,
                    config=cfg(retry_attempts=1),
                ).run_crack()

    def test_non_transient_error_propagates_unretried(self, spec,
                                                      digests):
        before = telemetry.counter("faults.retries").value
        with faults.armed("superstep.fetch:error=OSError,nth=1"):
            with pytest.raises(OSError):
                Sweep(
                    spec, LEET, LONG_WORDS, digests, config=cfg()
                ).run_crack()
        assert telemetry.counter("faults.retries").value == before

    def test_per_launch_path_dispatch_fault_retries(self, spec, digests,
                                                    baseline):
        c = SweepConfig(lanes=64, num_blocks=16, superstep=0)
        with faults.armed("superstep.dispatch:nth=2") as plan:
            got = Sweep(spec, LEET, LONG_WORDS, digests, config=c
                        ).run_crack()
        assert plan.fired
        assert full_hits(got) == full_hits(baseline)
        assert got.n_emitted == baseline.n_emitted

    def test_faults_armed_via_sweep_config(self, spec, digests, baseline):
        got = Sweep(
            spec, LEET, LONG_WORDS, digests,
            config=cfg(faults="superstep.dispatch:nth=2"),
        ).run_crack()
        assert faults.ACTIVE.fired == [("superstep.dispatch", 2)]
        assert full_hits(got) == full_hits(baseline)


# ---------------------------------------------------------------------------
# Fault matrix: packed dispatch (pump retry, demotion ladder)
# ---------------------------------------------------------------------------


class TestPackedSupervision:
    def _solo(self, spec, digest_sets):
        return [
            Sweep(spec, LEET, LONG_WORDS, d,
                  config=cfg(superstep=4)).run_crack()
            for d in digest_sets
        ]

    @pytest.fixture(scope="class")
    def digest_sets(self, spec):
        return [
            planted_digests(spec, LONG_WORDS, (0, 5)),
            planted_digests(spec, LONG_WORDS, (3, 200)),
        ]

    def test_pump_transient_retries_group_survives(self, spec,
                                                   digest_sets):
        solo = self._solo(spec, digest_sets)
        with faults.armed("packed.pump:nth=2") as plan:
            eng = Engine(cfg(superstep=4), auto=False)
            jobs = [eng.submit(spec, LEET, LONG_WORDS, d)
                    for d in digest_sets]
            eng._admit(wait=True)
            eng.run_until_idle()
            res = [j.result(timeout=0) for j in jobs]
        assert plan.fired
        for got, want in zip(res, solo):
            assert full_hits(got) == full_hits(want)
            assert got.n_emitted == want.n_emitted
            # Still packed: the group recovered instead of demoting.
            assert got.superstep.get("packed") == 2

    def test_pump_persistent_fault_demotes_to_solo(self, spec,
                                                   digest_sets):
        solo = self._solo(spec, digest_sets)
        before = telemetry.counter("engine.group_demotions").value
        with faults.armed("packed.pump:persist"):
            eng = Engine(cfg(superstep=4), auto=False)
            jobs = [eng.submit(spec, LEET, LONG_WORDS, d)
                    for d in digest_sets]
            eng._admit(wait=True)
            eng.run_until_idle()
            res = [j.result(timeout=0) for j in jobs]
        assert telemetry.counter(
            "engine.group_demotions"
        ).value == before + 1
        for got, want in zip(res, solo):
            assert full_hits(got) == full_hits(want)
            assert got.n_emitted == want.n_emitted
        assert eng.stats()["fused_groups"] == 0


# ---------------------------------------------------------------------------
# Fault matrix: engine ladder (restart, quarantine), admission, workers
# ---------------------------------------------------------------------------


class TestEngineLadder:
    def test_machine_restart_then_done_with_parity(self, spec, digests,
                                                   baseline):
        before = telemetry.counter("engine.job_restarts").value
        with faults.armed("superstep.fetch:nth=4") as plan:
            eng = Engine(cfg(retry_attempts=0), auto=False, pack=False,
                         job_retries=1)
            job = eng.submit(spec, LEET, LONG_WORDS, digests)
            eng.run_until_idle()
            res = job.result(timeout=0)
        assert plan.fired
        assert telemetry.counter(
            "engine.job_restarts"
        ).value == before + 1
        assert full_hits(res) == full_hits(baseline)
        assert res.n_emitted == baseline.n_emitted
        # The handle's async stream has no duplicates: replayed
        # checkpointed hits are muted on restart.
        got_q = [(h.word_index, h.variant_rank) for h in job.iter_hits()]
        assert got_q == [
            (h.word_index, h.variant_rank) for h in baseline.hits
        ]

    def test_quarantine_attaches_checkpoint(self, spec, digests):
        with faults.armed("superstep.fetch:nth=4,persist"):
            eng = Engine(cfg(retry_attempts=0), auto=False, pack=False,
                         job_retries=0)
            job = eng.submit(spec, LEET, LONG_WORDS, digests)
            eng.run_until_idle()
        with pytest.raises(JobFailed):
            job.result(timeout=0)
        assert job.state == "failed"
        assert job.checkpoint is not None
        assert job.checkpoint.cursor.word > 0  # real progress retained
        # The quarantine token resumes on a fresh engine, byte-exact.
        faults.clear()
        eng2 = Engine(cfg(), auto=False, pack=False)
        job2 = eng2.submit(spec, LEET, LONG_WORDS, digests,
                           resume_state=job.checkpoint)
        eng2.run_until_idle()
        res = job2.result(timeout=0)
        want = Sweep(spec, LEET, LONG_WORDS, digests,
                     config=cfg()).run_crack()
        assert full_hits(res) == full_hits(want)
        assert res.n_emitted == want.n_emitted

    def test_admission_build_fault_is_job_scoped(self, spec, digests,
                                                 baseline):
        with faults.armed("admission.build:nth=1"):
            eng = Engine(cfg(), auto=False, pack=False)
            j1 = eng.submit(spec, LEET, LONG_WORDS, digests)
            j2 = eng.submit(spec, LEET, LONG_WORDS, digests)
            eng.run_until_idle()
        assert j1.state == "failed"
        assert isinstance(j1.error, faults.FaultInjected)
        assert full_hits(j2.result(timeout=0)) == full_hits(baseline)

    def test_admission_worker_death_restarts_executor_once(
        self, spec, digests, baseline
    ):
        before = telemetry.counter("faults.worker_restarts").value
        with faults.armed("admission.build:error=WorkerDeath,nth=1"):
            eng = Engine(cfg(), auto=False, pack=False)
            job = eng.submit(spec, LEET, LONG_WORDS, digests)
            eng.run_until_idle()
            res = job.result(timeout=0)
        assert telemetry.counter(
            "faults.worker_restarts"
        ).value == before + 1
        assert full_hits(res) == full_hits(baseline)

    def test_chunk_compile_fault_restarts_worker_once(self, spec,
                                                      digests, baseline):
        c = cfg(stream_chunk_words=5)
        want = Sweep(spec, LEET, LONG_WORDS, digests, config=c).run_crack()
        assert want.stream["chunks_swept"] == 4
        assert full_hits(want) == full_hits(baseline)
        before = telemetry.counter("faults.worker_restarts").value
        with faults.armed("chunk.compile:nth=2"):
            got = Sweep(spec, LEET, LONG_WORDS, digests,
                        config=c).run_crack()
        assert telemetry.counter(
            "faults.worker_restarts"
        ).value == before + 1
        assert full_hits(got) == full_hits(want)
        assert got.n_emitted == want.n_emitted
        assert got.stream["chunks_swept"] == 4


# ---------------------------------------------------------------------------
# Fault matrix: checkpoint.write, device.init, serve.client
# ---------------------------------------------------------------------------


class TestCheckpointFaults:
    def test_periodic_write_failure_is_survived(self, spec, digests,
                                                baseline, tmp_path):
        path = str(tmp_path / "ck.json")
        before = telemetry.counter("faults.checkpoint_errors").value
        with faults.armed("checkpoint.write:nth=2"):
            got = Sweep(
                spec, LEET, LONG_WORDS, digests,
                config=cfg(checkpoint_path=path, checkpoint_every_s=0.0),
            ).run_crack()
        assert full_hits(got) == full_hits(baseline)
        assert telemetry.counter(
            "faults.checkpoint_errors"
        ).value == before + 1
        # The final forced save landed and loads clean.
        probe = Sweep(spec, LEET, LONG_WORDS, digests, config=cfg())
        state = load_checkpoint(path, probe.fingerprint)
        assert state is not None
        assert state.cursor.word == len(LONG_WORDS)
        assert state.n_hits == baseline.n_hits

    def test_device_init_fault_survived_by_cli_retry_layer(
        self, spec, digests, baseline
    ):
        """device.init's documented recovery is the rebuild-and-resume
        layer above the sweep: the CLI's --retries supervisor
        (_run_with_retries) — exercised here directly on the real
        function."""
        from hashcat_a5_table_generator_tpu.cli import _run_with_retries

        with faults.armed("device.init:nth=1") as plan:
            res = _run_with_retries(
                lambda resume: Sweep(
                    spec, LEET, LONG_WORDS, digests, config=cfg()
                ).run_crack(resume=resume),
                retries=1, default_resume=True, label="crack sweep",
            )
        assert plan.fired == [("device.init", 1)]
        assert full_hits(res) == full_hits(baseline)
        assert res.n_emitted == baseline.n_emitted


class TestServeClientFault:
    def test_client_fault_is_protocol_scoped(self):
        with faults.armed("serve.client:nth=1"):
            eng = Engine(cfg(), auto=False)
            fin = io.StringIO(
                json.dumps({"op": "stats"}) + "\n"
                + json.dumps({"op": "stats"}) + "\n"
                + json.dumps({"op": "shutdown"}) + "\n"
            )
            fout = io.StringIO()
            serve_stdio(eng, fin, fout)
            eng.close()
        events = [json.loads(l) for l in fout.getvalue().splitlines()]
        assert [e.get("event") for e in events] == [
            "error", "stats", "bye"
        ]
        assert "FaultInjected" in events[0]["error"]


class TestClientTimeout:
    def test_idle_client_dropped_engine_keeps_serving(self, tmp_path):
        eng = Engine(cfg(), auto=True)
        path = str(tmp_path / "serve.sock")
        ready = threading.Event()
        t = threading.Thread(
            target=serve_socket, args=(eng, path),
            kwargs=dict(client_timeout=0.3, ready=ready.set),
            daemon=True,
        )
        t.start()
        assert ready.wait(10)
        idle = socket.socket(socket.AF_UNIX)
        idle.connect(path)
        t0 = time.monotonic()
        assert idle.recv(4096) == b""  # server closed the idle session
        assert time.monotonic() - t0 < 5.0
        idle.close()
        # The engine (and the listener) survived the drop.
        live = socket.socket(socket.AF_UNIX)
        live.connect(path)
        f = live.makefile("rw")
        f.write(json.dumps({"op": "stats"}) + "\n")
        f.flush()
        assert json.loads(f.readline())["event"] == "stats"
        f.write(json.dumps({"op": "shutdown"}) + "\n")
        f.flush()
        assert json.loads(f.readline())["event"] == "bye"
        live.close()
        t.join(10)
        eng.close()

    def test_reconnecting_client_adopts_dropped_sessions_jobs(
        self, tmp_path, spec, digests
    ):
        """The --client-timeout contract's second half (PERF.md §23):
        the socket server's job registry is shared across connections,
        so a client dropped mid-job reconnects and controls the job by
        id — here pausing it and receiving the checkpoint on the NEW
        session."""
        eng = Engine(cfg(), auto=True)
        path = str(tmp_path / "serve.sock")
        ready = threading.Event()
        t = threading.Thread(
            target=serve_socket, args=(eng, path),
            kwargs=dict(ready=ready.set), daemon=True,
        )
        t.start()
        assert ready.wait(10)
        c1 = socket.socket(socket.AF_UNIX)
        c1.connect(path)
        f1 = c1.makefile("rw")
        f1.write(json.dumps({
            "op": "submit", "id": "adopt-me",
            "table_map": {
                k.decode(): [v.decode() for v in vals]
                for k, vals in LEET.items()
            },
            "words": [w.decode() for w in LONG_WORDS],
            "digest_list": [d.hex() for d in digests],
        }) + "\n")
        f1.flush()
        assert json.loads(f1.readline())["event"] == "accepted"
        c1.close()  # the client "dies" mid-job
        c2 = socket.socket(socket.AF_UNIX)
        c2.connect(path)
        f2 = c2.makefile("rw")
        f2.write(json.dumps({"op": "pause", "id": "adopt-me"}) + "\n")
        f2.flush()
        ev = json.loads(f2.readline())
        # Raced completion is legal (tiny job); either way the NEW
        # session got the settling event for the adopted job.
        assert ev["id"] == "adopt-me"
        assert ev["event"] in ("paused", "done")
        if ev["event"] == "paused":
            assert ev["checkpoint"]["fingerprint"]
        f2.write(json.dumps({"op": "shutdown"}) + "\n")
        f2.flush()
        assert json.loads(f2.readline())["event"] == "bye"
        c2.close()
        t.join(10)
        eng.close()


# ---------------------------------------------------------------------------
# Corrupt checkpoints (typed errors) + atomic writes
# ---------------------------------------------------------------------------


class TestCheckpointCorruption:
    def test_truncated_json_raises_typed(self, tmp_path):
        path = str(tmp_path / "ck.json")
        with open(path, "w") as fh:
            fh.write('{"fingerprint": "abc", "cursor"')
        with pytest.raises(CheckpointCorrupt) as exc:
            load_checkpoint(path, "abc")
        assert path in str(exc.value)
        assert "truncated" in str(exc.value)

    def test_schema_breakage_raises_typed(self, tmp_path):
        path = str(tmp_path / "ck.json")
        from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
            FORMAT_VERSION,
        )

        with open(path, "w") as fh:
            json.dump({"fingerprint": "abc", "version": FORMAT_VERSION,
                       "cursor": {"word": "NaN-ish"}}, fh)
        with pytest.raises(CheckpointCorrupt, match="field parse"):
            load_checkpoint(path, "abc")

    def test_corrupt_manifest_raises_typed(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        with open(path, "w") as fh:
            fh.write("not json at all")
        with pytest.raises(CheckpointCorrupt):
            check_bucket_manifest(path, {16: "fp"})

    def test_corrupt_is_a_value_error(self):
        # The CLI's existing ValueError surface still catches it; the
        # dedicated hint branch must come first.
        assert issubclass(CheckpointCorrupt, ValueError)

    def test_cli_prints_remediation_hint(self, tmp_path, spec, digests):
        from hashcat_a5_table_generator_tpu import cli

        d = tmp_path
        (d / "dict.txt").write_bytes(b"\n".join(LONG_WORDS) + b"\n")
        (d / "leet.table").write_bytes(
            b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n"
        )
        (d / "left.txt").write_bytes(
            b"\n".join(dg.hex().encode() for dg in digests) + b"\n"
        )
        ck = d / "ck.json"
        ck.write_text('{"torn":')
        with pytest.raises(SystemExit) as exc:
            cli.main([
                str(d / "dict.txt"), "-t", str(d / "leet.table"),
                "--backend", "device", "--digests", str(d / "left.txt"),
                "--buckets", "none", "--lanes", "64", "--blocks", "16",
                "--checkpoint", str(ck),
            ])
        msg = str(exc.value)
        assert "corrupt" in msg and "remediation" in msg
        assert "--no-resume" in msg

    def test_atomic_write_replaces_and_survives(self, tmp_path):
        path = str(tmp_path / "out.json")
        atomic_write_text(path, '{"v": 1}')
        atomic_write_text(path, '{"v": 2}')
        with open(path) as fh:
            assert json.load(fh) == {"v": 2}
        # No tmp litter left behind.
        assert os.listdir(str(tmp_path)) == ["out.json"]

    def test_manifest_roundtrip_via_atomic_writer(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        save_bucket_manifest(path, {16: "fp16", 32: "fp32"})
        assert check_bucket_manifest(path, {16: "fp16", 32: "fp32"})


# ---------------------------------------------------------------------------
# SIGKILL crash-recovery soak (slow tier)
# ---------------------------------------------------------------------------


_SERVE_DRIVER = (
    "import sys\n"
    "import jax\n"
    "jax.config.update('jax_platforms', 'cpu')\n"
    "from hashcat_a5_table_generator_tpu.cli import main\n"
    "sys.exit(main(sys.argv[1:]))"
)


def _connect(path, timeout=30.0):
    deadline = time.monotonic() + timeout
    while True:
        try:
            s = socket.socket(socket.AF_UNIX)
            s.connect(path)
            return s
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


@pytest.mark.slow
class TestCrashRecoverySoak:
    def test_sigkill_restart_resubmit_byte_parity(self, tmp_path, spec,
                                                  digests, baseline):
        """Drive ``a5gen serve`` over the unix socket, SIGKILL it at a
        fault-chosen superstep boundary, restart a fresh engine,
        resubmit from the on-disk checkpoint, and assert the recovered
        run's hit stream reproduces the uninterrupted run byte-exactly
        (with run 1's delivered hits a prefix of it)."""
        sock = str(tmp_path / "serve.sock")
        ck = str(tmp_path / "job.ck.json")
        job_doc = {
            "op": "submit", "id": "soak",
            "table_map": {
                k.decode(): [v.decode() for v in vals]
                for k, vals in LEET.items()
            },
            "words": [w.decode() for w in LONG_WORDS],
            "digest_list": [d.hex() for d in digests],
            "config": {"checkpoint_path": ck, "checkpoint_every_s": 0.0},
        }
        serve_argv = ["serve", "--socket", sock, "--lanes", "64",
                      "--blocks", "16", "--superstep", "1"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["A5GEN_FAULTS"] = "superstep.fetch:kill,nth=3"

        p1 = subprocess.Popen(
            [sys.executable, "-c", _SERVE_DRIVER, *serve_argv],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        run1_hits = []
        try:
            c1 = _connect(sock, timeout=120.0)
            f1 = c1.makefile("rw")
            f1.write(json.dumps(job_doc) + "\n")
            f1.flush()
            assert json.loads(f1.readline())["event"] == "accepted"
            for line in f1:  # EOF when the process is SIGKILLed
                ev = json.loads(line)
                if ev.get("event") == "hit":
                    run1_hits.append(
                        (ev["word_index"], int(ev["rank"]),
                         ev["plain_hex"], ev["digest"])
                    )
                elif ev.get("event") == "done":
                    pytest.fail("fault did not kill the engine mid-sweep")
            c1.close()
            assert p1.wait(timeout=60) == -9  # SIGKILL, not a clean exit
        finally:
            if p1.poll() is None:
                p1.kill()
                p1.wait()

        # The lagged-boundary checkpoint is on disk and intact.
        probe = Sweep(spec, LEET, LONG_WORDS, digests, config=cfg())
        state = load_checkpoint(ck, probe.fingerprint)
        assert state is not None
        assert 0 < state.cursor.word <= len(LONG_WORDS)

        env2 = dict(env)
        env2.pop("A5GEN_FAULTS")
        p2 = subprocess.Popen(
            [sys.executable, "-c", _SERVE_DRIVER, *serve_argv],
            env=env2, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        run2_hits = []
        try:
            c2 = _connect(sock, timeout=120.0)
            f2 = c2.makefile("rw")
            resub = dict(job_doc)
            resub["checkpoint"] = state_to_doc(state)
            f2.write(json.dumps(resub) + "\n")
            f2.flush()
            assert json.loads(f2.readline())["event"] == "accepted"
            done = None
            for line in f2:
                ev = json.loads(line)
                if ev.get("event") == "hit":
                    run2_hits.append(
                        (ev["word_index"], int(ev["rank"]),
                         ev["plain_hex"], ev["digest"])
                    )
                elif ev.get("event") == "done":
                    done = ev
                    break
            assert done is not None and done["resumed"]
            f2.write(json.dumps({"op": "shutdown"}) + "\n")
            f2.flush()
            p2.wait(timeout=60)
        finally:
            if p2.poll() is None:
                p2.kill()
                p2.wait()

        want = [
            (h.word_index, h.variant_rank, h.candidate.hex(),
             h.digest_hex)
            for h in baseline.hits
        ]
        # Byte parity: the recovered run (checkpoint replay + the
        # resumed sweep) reproduces the uninterrupted hit stream
        # exactly, and run 1's delivered hits are a prefix of it — the
        # kill-at-a-fetch-boundary + checkpoint-every-boundary choice
        # makes the concatenated (deduplicated) stream equal run 2's.
        assert run2_hits == want
        assert run1_hits == want[: len(run1_hits)]
        assert done["n_hits"] == baseline.n_hits
        assert done["n_emitted"] == baseline.n_emitted


@pytest.mark.slow
class TestRefuseCrashSoak:
    def test_sigkill_after_refuse_cursor_carries_over(self, tmp_path,
                                                      spec):
        """Churn + crash (PERF.md §28): four packed tenants, two cancel
        mid-flight, the engine re-fuses the survivors (the client sees
        the ``refused`` event), and THEN the serve process is
        SIGKILLed.  The survivors' on-disk checkpoints — cursors in
        rank-stride units, written at every boundary across the
        re-fuse — resume on a fresh engine to the uninterrupted byte
        stream, with run 1's delivered hits a prefix of it: the cursor
        is interchangeable between the original group, the re-fused
        group, and a solo resume."""
        sock = str(tmp_path / "churn.sock")
        n = 4
        words, digests, cks = [], [], []
        for i in range(n):
            rot = (LONG_WORDS[i:] + LONG_WORDS[:i]) * 2
            d = planted_digests(spec, rot)
            d += [hashlib.md5(b"tenant-%d" % i).digest()]
            words.append(rot)
            digests.append(d)
            cks.append(str(tmp_path / ("t%d.ck.json" % i)))
        docs = [
            {
                "op": "submit", "id": "t%d" % i,
                "table_map": {
                    k.decode(): [v.decode() for v in vals]
                    for k, vals in LEET.items()
                },
                "words": [w.decode() for w in words[i]],
                "digest_list": [d.hex() for d in digests[i]],
                "config": {
                    "checkpoint_path": cks[i],
                    "checkpoint_every_s": 0.0,
                },
            }
            for i in range(n)
        ]
        serve_argv = ["serve", "--socket", sock, "--lanes", "64",
                      "--blocks", "16", "--superstep", "1"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["A5GEN_REFUSE"] = "0.9"

        p1 = subprocess.Popen(
            [sys.executable, "-c", _SERVE_DRIVER, *serve_argv],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        run1 = {d["id"]: [] for d in docs}
        refused = None
        try:
            c1 = _connect(sock, timeout=120.0)
            c1.settimeout(120.0)
            f1 = c1.makefile("rw")
            # One flush for the burst: the admission-build window IS
            # the packing window, so all four fuse into one group.
            for doc in docs:
                f1.write(json.dumps(doc) + "\n")
            f1.flush()
            accepted = set()
            while len(accepted) < n:
                ev = json.loads(f1.readline())
                assert ev["event"] == "accepted"
                accepted.add(ev["id"])
            # Cancel only after the FIRST hit: a cancel that lands
            # while the burst is still building is honored pre-
            # admission and the departing pair never joins the group
            # at all (no departure, nothing to re-fuse).
            cancelled = False
            for line in f1:
                ev = json.loads(line)
                if ev.get("event") == "hit":
                    run1[ev["id"]].append(
                        (ev["word_index"], int(ev["rank"]),
                         ev["plain_hex"], ev["digest"])
                    )
                    if not cancelled:
                        cancelled = True
                        f1.write(
                            json.dumps({"op": "cancel", "id": "t0"})
                            + "\n"
                        )
                        f1.write(
                            json.dumps({"op": "cancel", "id": "t1"})
                            + "\n"
                        )
                        f1.flush()
                elif ev.get("event") == "refused":
                    refused = ev
                    break
                elif ev.get("event") == "done":
                    pytest.fail(
                        "a survivor drained before the re-fuse landed"
                    )
            assert refused is not None and refused["id"] in ("t2", "t3")
            assert 0.0 < refused["fill"] < 0.9
            # Let the re-fused group cross a few boundaries (the
            # checkpoint writes at EVERY boundary) before pulling the
            # plug; bounded so fast hosts don't drain the survivors.
            extra = 0
            c1.settimeout(1.0)
            try:
                while extra < 4:
                    line = f1.readline()
                    if not line:
                        break
                    ev = json.loads(line)
                    extra += 1
                    if ev.get("event") == "hit":
                        run1[ev["id"]].append(
                            (ev["word_index"], int(ev["rank"]),
                             ev["plain_hex"], ev["digest"])
                        )
                    elif ev.get("event") == "done":
                        break
            except (socket.timeout, TimeoutError):
                pass
            p1.kill()  # SIGKILL — no shutdown hooks, no final flush
            assert p1.wait(timeout=60) == -9
            c1.close()
        finally:
            if p1.poll() is None:
                p1.kill()
                p1.wait()

        want = {
            jid: [
                (h.word_index, h.variant_rank, h.candidate.hex(),
                 h.digest_hex)
                for h in Sweep(
                    spec, LEET, words[i], digests[i], config=cfg()
                ).run_crack().hits
            ]
            for i, jid in ((2, "t2"), (3, "t3"))
        }
        for jid in ("t2", "t3"):
            assert run1[jid] == want[jid][: len(run1[jid])]

        probe = Sweep(spec, LEET, words[2], digests[2], config=cfg())
        state = load_checkpoint(cks[2], probe.fingerprint)
        assert state is not None
        assert 0 < state.cursor.word <= len(words[2])

        p2 = subprocess.Popen(
            [sys.executable, "-c", _SERVE_DRIVER, *serve_argv],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        run2 = []
        try:
            c2 = _connect(sock, timeout=120.0)
            c2.settimeout(120.0)
            f2 = c2.makefile("rw")
            resub = dict(docs[2])
            resub["checkpoint"] = state_to_doc(state)
            f2.write(json.dumps(resub) + "\n")
            f2.flush()
            assert json.loads(f2.readline())["event"] == "accepted"
            done = None
            for line in f2:
                ev = json.loads(line)
                if ev.get("event") == "hit":
                    run2.append(
                        (ev["word_index"], int(ev["rank"]),
                         ev["plain_hex"], ev["digest"])
                    )
                elif ev.get("event") == "done":
                    done = ev
                    break
            assert done is not None and done["resumed"]
            f2.write(json.dumps({"op": "shutdown"}) + "\n")
            f2.flush()
            p2.wait(timeout=60)
        finally:
            if p2.poll() is None:
                p2.kill()
                p2.wait()

        # Byte parity: checkpoint replay + the resumed sweep reproduce
        # the uninterrupted survivor stream exactly, through a cursor
        # that crossed a re-fuse boundary in run 1.
        assert run2 == want["t2"]

"""Device-resident superstep executor (PERF.md §15): on/off parity of
hits and candidate streams, overflow→replay, mid-superstep resume, the
escape hatches, and the bench A/B record shape.

The superstep path must be STREAM-INVISIBLE: every test here runs the
same sweep through the per-launch pipeline (``superstep=0``) and the
superstep executor and pins the results equal — hits by full
(word_index, rank, candidate) tuples, candidates byte-for-byte.
"""

import hashlib
import io
import json
import pathlib
import subprocess
import sys

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.runtime import (
    CandidateWriter,
    HitRecorder,
    Sweep,
    SweepConfig,
)

REPO = pathlib.Path(__file__).resolve().parent.parent

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a"]


def oracle_lines(spec, sub_map, words):
    out = []
    for w in words:
        out.extend(
            iter_candidates(
                w, sub_map, spec.min_substitute, spec.max_substitute,
                substitute_all=spec.mode.startswith("suball"),
                reverse=spec.mode in ("reverse", "suball-reverse"),
            )
        )
    return out


def hit_tuples(res):
    return [(h.word_index, h.variant_rank, h.candidate) for h in res.hits]


def run_crack(spec, sub_map, words, digests, *, superstep, devices=1,
              **cfg_kw):
    cfg = SweepConfig(lanes=64, num_blocks=16, superstep=superstep,
                      devices=devices, **cfg_kw)
    sweep = Sweep(spec, sub_map, words, digests, config=cfg)
    return sweep.run_crack()


class TestSuperstepParity:
    """superstep on == superstep off, bit for bit."""

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_hits_and_counts_equal_per_launch(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[0], oracle[len(oracle) // 3], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(40)]

        off = run_crack(spec, LEET, WORDS, digests, superstep=0)
        on = run_crack(spec, LEET, WORDS, digests, superstep=None)
        assert on.n_emitted == off.n_emitted == len(oracle)
        assert hit_tuples(on) == hit_tuples(off)
        assert {h.candidate for h in on.hits} == set(planted)
        # The executor really ran (off path reports no superstep stats).
        assert on.superstep["supersteps"] >= 1
        assert on.superstep["launches_per_fetch"] >= 1
        assert off.superstep == {}

    def test_suball_with_fallback_words_interleaved(self):
        # Boundary-crossing ReplaceAll hazard: 'acb' words stay
        # oracle-routed; the superstep cursor must skip them and the hit
        # list must interleave identically with the per-launch path.
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
        words = [b"zz", b"acb", b"za", b"zacb", b"azz"]
        spec = AttackSpec(mode="suball", algo="md5")
        fb_cand = oracle_lines(spec, sub, [b"acb"])[-1]
        dev_cand = oracle_lines(spec, sub, [b"azz"])[-1]
        digests = [hashlib.md5(fb_cand).digest(),
                   hashlib.md5(dev_cand).digest()]

        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=None)
        sweep = Sweep(spec, sub, words, digests, config=cfg)
        assert sweep.fallback_rows, "fixture must exercise fallback"
        on = sweep.run_crack()
        off = run_crack(spec, sub, words, digests, superstep=0)
        assert hit_tuples(on) == hit_tuples(off)
        assert {h.candidate for h in on.hits} == {fb_cand, dev_cand}
        assert on.superstep["supersteps"] >= 1

    @pytest.mark.slow  # ~7 s on the tier-1 host; multi-device equality
    # keeps default coverage via the sharded parity arms in
    # test_sharding.
    def test_multi_device_equals_per_launch(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[1], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]

        off = run_crack(spec, LEET, WORDS, digests, superstep=0, devices=8)
        on = run_crack(spec, LEET, WORDS, digests, superstep=None, devices=8)
        one = run_crack(spec, LEET, WORDS, digests, superstep=None)
        assert hit_tuples(on) == hit_tuples(off) == hit_tuples(one)
        assert on.n_emitted == off.n_emitted == one.n_emitted
        assert on.superstep["supersteps"] >= 1

    def test_windowed_plan_parity(self):
        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=1)
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[0]).digest(),
                   hashlib.md5(oracle[-1]).digest()]
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=None)
        sweep = Sweep(spec, LEET, WORDS, digests, config=cfg)
        assert sweep.plan.windowed, "window must engage the DP plan"
        on = sweep.run_crack()
        off = run_crack(spec, LEET, WORDS, digests, superstep=0)
        assert hit_tuples(on) == hit_tuples(off)
        assert on.n_emitted == off.n_emitted == len(oracle)

    def test_candidates_stream_byte_identical(self):
        # Candidates mode must ship every lane's bytes regardless, so the
        # superstep applies to crack mode only — the flag must be a
        # byte-exact no-op on the candidate stream.
        spec = AttackSpec(mode="default", algo="md5")

        def stream(sstep):
            cfg = SweepConfig(lanes=64, num_blocks=16, superstep=sstep)
            sweep = Sweep(spec, LEET, WORDS, config=cfg)
            buf = io.BytesIO()
            with CandidateWriter(buf) as w:
                sweep.run_candidates(w)
            return buf.getvalue()

        assert stream(None) == stream(0)


class TestOverflowReplay:
    def test_hit_buffer_overflow_replays_exactly(self):
        """Planted hit density above the cap: the device buffer drops
        entries, the driver replays that superstep per-launch, and the
        final hit list is byte-identical to the per-launch run."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, [b"password", b"sesame"])
        dense = [hashlib.md5(c).digest() for c in oracle[:40]]

        off = run_crack(spec, LEET, WORDS, dense, superstep=0)
        on = run_crack(spec, LEET, WORDS, dense, superstep=None,
                       superstep_hit_cap=8)
        assert on.superstep["replays"] >= 1
        assert hit_tuples(on) == hit_tuples(off)
        assert on.n_hits == off.n_hits == 40
        assert on.n_emitted == off.n_emitted

    @pytest.mark.slow  # ~8 s on the tier-1 host; the exact-cap edge
    # keeps default coverage via test_overflow_replays_exactly, which
    # drives the same replay bookkeeping past the cap.
    def test_cap_exactly_reached_needs_no_replay(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, [b"password"])
        planted = sorted(set(oracle[:4]))
        digests = [hashlib.md5(c).digest() for c in planted]
        on = run_crack(spec, LEET, WORDS, digests, superstep=None,
                       superstep_hit_cap=len(planted))
        off = run_crack(spec, LEET, WORDS, digests, superstep=0)
        assert on.superstep["replays"] == 0
        assert hit_tuples(on) == hit_tuples(off)


class TestSuperstepResume:
    def test_interrupted_mid_superstep_resumes_identically(self, tmp_path):
        """A crash between supersteps leaves a boundary checkpoint; the
        resumed run's final hit list equals the uninterrupted run's."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[3], oracle[-2]})
        digests = [hashlib.md5(c).digest() for c in planted]

        want = run_crack(spec, LEET, WORDS, digests, superstep=None)

        path = str(tmp_path / "ss.json")
        # superstep=1: one launch per superstep -> several superstep
        # boundaries (and checkpoints, every_s=0) inside the sweep.
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                          checkpoint_path=path, checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        from hashcat_a5_table_generator_tpu.runtime import load_checkpoint

        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None
        assert partial.cursor.word < len(WORDS)

        second = Sweep(spec, LEET, WORDS, digests, config=cfg)
        got = second.run_crack()
        assert got.resumed
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )
        assert {h.candidate for h in got.hits} == set(planted)

    def test_superstep_checkpoint_resumes_on_per_launch_path(self, tmp_path):
        """A superstep-boundary checkpoint is a plain (word, rank) cursor:
        resuming it with the executor OFF completes the identical sweep."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[-1]).digest()]
        path = str(tmp_path / "cross.json")
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                          checkpoint_path=path, checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())

        cfg2 = SweepConfig(lanes=64, num_blocks=16, superstep=0,
                           checkpoint_path=path, checkpoint_every_s=0.0)
        got = Sweep(spec, LEET, WORDS, digests, config=cfg2).run_crack()
        assert got.resumed
        want = run_crack(spec, LEET, WORDS, digests, superstep=0)
        assert hit_tuples(got) == hit_tuples(want)


class TestEscapeHatches:
    def test_env_off_disables_executor(self, monkeypatch):
        monkeypatch.setenv("A5GEN_SUPERSTEP", "off")
        spec = AttackSpec(mode="default", algo="md5")
        digests = [hashlib.md5(b"nope").digest()]
        res = run_crack(spec, LEET, WORDS, digests, superstep=None)
        assert res.superstep == {}

    def test_config_zero_disables_executor(self):
        spec = AttackSpec(mode="default", algo="md5")
        digests = [hashlib.md5(b"nope").digest()]
        res = run_crack(spec, LEET, WORDS, digests, superstep=0)
        assert res.superstep == {}

    def test_packed_layout_falls_back_to_per_launch(self):
        # The executor needs the fixed-stride layout; an explicit packed
        # request keeps the per-launch pipeline, stream unchanged.
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[-1]).digest()]
        res = run_crack(spec, LEET, WORDS, digests, superstep=None,
                        packed_blocks=True)
        assert res.superstep == {}
        assert {h.candidate for h in res.hits} == {oracle[-1]}

    def test_cli_superstep_arg(self):
        from hashcat_a5_table_generator_tpu.cli import build_parser

        ap = build_parser()
        assert ap.parse_args(["d", "-t", "x"]).superstep is None
        assert ap.parse_args(["d", "-t", "x", "--superstep", "off"]
                             ).superstep == 0
        assert ap.parse_args(["d", "-t", "x", "--superstep", "auto"]
                             ).superstep is None
        assert ap.parse_args(["d", "-t", "x", "--superstep", "8"]
                             ).superstep == 8
        with pytest.raises(SystemExit):
            ap.parse_args(["d", "-t", "x", "--superstep", "-3"])


def test_bench_superstep_ab_record_shape():
    """The §15 measurement instrument: one JSON line, both arms, the
    host-overhead ratio the acceptance criterion reads."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--superstep-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "400", "--seconds", "1"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "superstep_host_overhead_ab"
    for arm in ("per_launch", "superstep"):
        assert rec[arm]["hashes_per_sec"] > 0
        assert rec[arm]["launches"] >= 16
        assert rec[arm]["host_s_per_step"] >= 0
    # The superstep arm cuts zero blocks on the host by construction.
    assert rec["superstep"]["cut_s_per_step"] == 0.0
    assert rec["host_overhead_ratio"] > 1.0

"""Per-slot piece emission parity suite (PERF.md §17).

The emission scheme rewrite (per-byte unit scan -> per-slot pieces with
host-precomputed group variant tables) must be BYTE-IDENTICAL on every
path it landed on: the XLA splices (``expand_matches`` / ``expand_suball``)
and the fused Pallas kernels (every tier: scalar/general x full/windowed x
match/suball, closed plans, NTLM's split pieces, multi-hash-block widths).
These tests fuzz randomized tables and wordlists through BOTH schemes —
``A5GEN_EMIT=bytescan`` (the escape hatch, selected here by simply not
passing a schema) against the per-slot default — and require exact
equality of emitted candidates / digests.
"""

import zlib

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    plan_arrays,
    table_arrays,
)
from hashcat_a5_table_generator_tpu.ops import pallas_expand as pe
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.expand_matches import expand_matches
from hashcat_a5_table_generator_tpu.ops.expand_suball import expand_suball
from hashcat_a5_table_generator_tpu.ops.packing import (
    build_piece_schema,
    pack_words,
    piece_schema_for,
)
from hashcat_a5_table_generator_tpu.runtime.env import emit_scheme
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import BUILTIN_LAYOUTS

MODES = ("default", "reverse", "suball", "suball-reverse")
ALGOS = ("md5", "md4", "sha1", "ntlm")

NB, STRIDE = 8, 128


def rand_table(rng, *, k_opts=3, val_len=3, alphabet=b"abcdefgh"):
    """Random single-byte-key substitution map over a small alphabet."""
    sub = {}
    for key in rng.choice(list(alphabet), size=4, replace=False):
        n_opt = int(rng.integers(1, k_opts + 1))
        vals = []
        for _ in range(n_opt):
            w = int(rng.integers(1, val_len + 1))
            vals.append(bytes(
                rng.choice(list(b"XYZ0123"), size=w).astype(np.uint8)
            ))
        sub[bytes([int(key)])] = vals
    return sub


def rand_words(rng, n=6, width=9, alphabet=b"abcdefgh~!"):
    return [
        bytes(rng.choice(list(alphabet),
                         size=int(rng.integers(1, width))).astype(np.uint8))
        for _ in range(n)
    ]


def _setup(spec, sub, words, **plan_kw):
    ct = compile_table(sub)
    plan = build_plan(spec, ct, pack_words(words), **plan_kw)
    schema = piece_schema_for(plan, ct)
    batch, _, _ = make_blocks(
        plan, start_word=0, start_rank=0, max_variants=NB * STRIDE,
        max_blocks=NB, fixed_stride=STRIDE,
    )
    b = block_arrays(pad_batch(batch, NB), num_blocks=NB)
    return ct, plan, schema, plan_arrays(plan), table_arrays(ct), b


def run_xla(spec, plan, parr, t, b, pieces):
    common = dict(
        num_lanes=NB * STRIDE, out_width=plan.out_width,
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute, block_stride=STRIDE,
        win_v=parr.get("win_v"), pieces=pieces,
    )
    if spec.mode in ("default", "reverse"):
        return expand_matches(
            parr["tokens"], parr["lengths"], parr["match_pos"],
            parr["match_len"], parr["match_radix"],
            parr["match_val_start"], t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], b["offset"], **common,
        )
    return expand_suball(
        parr["tokens"], parr["lengths"], parr["pat_radix"],
        parr["pat_val_start"], parr["seg_orig_start"],
        parr["seg_orig_len"], parr["seg_pat"],
        parr.get("cval_bytes", t["val_bytes"]),
        parr.get("cval_len", t["val_len"]),
        b["word"], b["base"], b["count"], b["offset"],
        close_next=parr.get("close_next"),
        close_mul=parr.get("close_mul"), **common,
    )


def run_pallas(spec, plan, ct, parr, t, b, pieces, *, algo,
               scalar_units=None):
    k = pe.k_vals_for(plan)
    if scalar_units is None:
        scalar_units = pe.scalar_units_for(plan)
    common = dict(
        num_lanes=NB * STRIDE, out_width=int(plan.out_width),
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute, block_stride=STRIDE,
        k_opts=k, algo=algo, interpret=True,
        scalar_units=scalar_units, win_v=parr.get("win_v"),
        pieces=pieces,
    )
    if spec.mode in ("default", "reverse"):
        return pe.fused_expand_md5(
            parr["tokens"], parr["lengths"], parr["match_pos"],
            parr["match_len"], parr["match_radix"],
            parr["match_val_start"], t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], **common,
        )
    return pe.fused_expand_suball_md5(
        parr["tokens"], parr["lengths"], parr["pat_radix"],
        parr["pat_val_start"], parr["seg_orig_start"],
        parr["seg_orig_len"], parr["seg_pat"],
        parr.get("cval_bytes", t["val_bytes"]),
        parr.get("cval_len", t["val_len"]),
        b["word"], b["base"], b["count"],
        close_next=parr.get("close_next"),
        close_mul=parr.get("close_mul"), **common,
    )


def assert_xla_parity(spec, plan, schema, parr, t, b):
    """Candidate buffers of both schemes must agree on emitted lanes."""
    assert schema is not None, "plan unexpectedly piece-ineligible"
    c0, l0, w0, e0 = map(np.asarray, run_xla(spec, plan, parr, t, b, None))
    c1, l1, w1, e1 = map(np.asarray, run_xla(spec, plan, parr, t, b,
                                             schema))
    assert (e0 == e1).all()
    assert (l0[e0] == l1[e0]).all()
    assert (w0[e0] == w1[e0]).all()
    assert (c0[e0] == c1[e0]).all()
    return int(e0.sum())


def assert_pallas_parity(spec, plan, ct, schema, parr, t, b, *, algo,
                         scalar_units=None):
    assert schema is not None, "plan unexpectedly piece-ineligible"
    s0, e0 = map(np.asarray, run_pallas(
        spec, plan, ct, parr, t, b, None, algo=algo,
        scalar_units=scalar_units,
    ))
    s1, e1 = map(np.asarray, run_pallas(
        spec, plan, ct, parr, t, b, schema, algo=algo,
        scalar_units=scalar_units,
    ))
    assert (e0 == e1).all()
    assert (s0[e0] == s1[e0]).all()
    return int(e0.sum())


class TestXlaFuzzParity:
    """The XLA splice twins, fuzzed (algo-independent: the splice
    produces candidate BYTES; the hash stage is shared downstream)."""

    @pytest.mark.parametrize("mode", MODES)
    def test_random_tables(self, mode):
        rng = np.random.default_rng(hash(mode) % (1 << 31))
        emitted = 0
        for trial in range(4):
            spec = AttackSpec(mode=mode, algo="md5")
            words = rand_words(rng)
            sub = rand_table(rng)
            ct, plan, schema, parr, t, b = _setup(spec, sub, words)
            if schema is None:
                continue  # rare geometry rejection — covered elsewhere
            emitted += assert_xla_parity(spec, plan, schema, parr, t, b)
        assert emitted > 0

    @pytest.mark.parametrize("mode", ("default", "suball"))
    def test_windowed_plans(self, mode):
        # Tight window over many matches: every char is a key, so a
        # 12-char word's windowed total (~80) undercuts the full 2^12
        # space by far more than the 2x gate.
        spec = AttackSpec(mode=mode, algo="md5", min_substitute=1,
                          max_substitute=2)
        sub = {bytes([c]): [b"Q", b"RR"] for c in b"abcdef"}
        words = [b"abcdefabcdef", b"fedcbafedcba", b"abc"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.windowed, "fixture must exercise the windowed decode"
        assert_xla_parity(spec, plan, schema, parr, t, b)

    def test_closed_suball_plan(self):
        sub = BUILTIN_LAYOUTS["qwerty-azerty"].to_substitution_map()
        spec = AttackSpec(mode="suball", algo="md5")
        words = [b"aqwzsxm,", b"marmalade", b"qqaazz", b"azerty"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.close_next is not None
        assert schema is not None and schema.closed
        assert assert_xla_parity(spec, plan, schema, parr, t, b) > 0


class TestPallasFuzzParity:
    """The fused kernels, fuzzed per (mode, algo) — interpret mode."""

    @pytest.mark.parametrize("mode,algo", [
        ("default", "md5"),
        # The (default, ntlm) and (reverse, sha1) arms cost ~7 s and
        # ~10 s interpret-mode on the tier-1 host; ntlm keeps a default
        # arm via (suball-reverse, ntlm) + the multiword-split test,
        # sha1 via (suball-reverse, sha1).
        pytest.param("default", "ntlm", marks=pytest.mark.slow),
        pytest.param("reverse", "sha1", marks=pytest.mark.slow),
        ("reverse", "md5"),
        ("suball", "md4"), ("suball", "md5"),
        ("suball-reverse", "ntlm"), ("suball-reverse", "sha1"),
    ])
    def test_general_kernel(self, mode, algo):
        rng = np.random.default_rng(hash((mode, algo)) % (1 << 31))
        spec = AttackSpec(mode=mode, algo=algo)
        words = rand_words(rng, n=5, width=8)
        sub = rand_table(rng)
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        if schema is None:
            pytest.skip("randomized geometry rejected the schema")
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo=algo,
            scalar_units=False,
        ) > 0

    @pytest.mark.parametrize("mode,algo", [
        ("default", "md5"), ("default", "ntlm"), ("default", "sha1"),
        ("default", "md4"), ("reverse", "md5"), ("suball", "md5"),
        ("suball-reverse", "ntlm"),
    ])
    def test_scalar_kernel(self, mode, algo):
        # K=1 tables (reverse modes clamp radix to 2 anyway; here the
        # table itself is 1:1 so default/suball hit K=1 too).
        rng = np.random.default_rng(hash((algo, mode)) % (1 << 31))
        spec = AttackSpec(mode=mode, algo=algo)
        words = rand_words(rng, n=5, width=8)
        sub = rand_table(rng, k_opts=1)
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        if schema is None:
            pytest.skip("randomized geometry rejected the schema")
        assert pe.scalar_units_for(plan)
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo=algo,
        ) > 0

    @pytest.mark.slow  # ~10 s interpret cost on the tier-1 host; the
    # multi-u32 piece × utf16 boundary fold keeps default coverage via
    # the suball NTLM parity test in test_pallas_expand.
    def test_ntlm_multiword_split_pieces(self):
        # 3-byte values on longer words force multi-u32 pieces whose
        # UTF-16LE expansion crosses word boundaries — the split-piece
        # case the terminator pseudo-byte must survive.
        spec = AttackSpec(mode="default", algo="ntlm")
        # A 5-byte key's skip span needs a 2-u32 piece, whose UTF-16LE
        # expansion crosses message-word boundaries.
        words = [b"xabcdex", b"abcdeabcde", b"zabcde", b"qq"]
        sub = {b"abcde": [b"XYZ", b"#"]}
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None
        assert max(g.n_words for g in schema.groups) >= 2
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo="ntlm",
            scalar_units=False,
        ) > 0

    @pytest.mark.slow  # ~8 s interpret cost on the tier-1 host; the
    # windowed decode keeps default coverage via the windowed parity
    # tests in test_pallas_expand and the windowed pack parity arm.
    def test_windowed_scalar_parity(self):
        spec = AttackSpec(mode="default", algo="md5", min_substitute=1,
                          max_substitute=2)
        sub = {bytes([c]): [b"QQ"] for c in b"abcdef"}
        words = [b"abcdefabcdef", b"fedcbafedcba", b"abc"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.windowed and pe.scalar_units_for(plan)
        assert_pallas_parity(spec, plan, ct, schema, parr, t, b,
                             algo="md5")

    @pytest.mark.slow  # ~12 s interpret cost on the tier-1 host
    # (runs both kernel tiers back to back); each tier keeps its own
    # default arm via the scalar/general suball parity tests above.
    def test_windowed_suball_parity_both_tiers(self):
        # The suball windowed piece kernels: the scalar tier packs the
        # DP walk's chosen bits through the per-block bitpos ref; the
        # general tier resolves each column's digit via sel_slot.
        spec = AttackSpec(mode="suball", algo="md5", min_substitute=1,
                          max_substitute=2)
        sub = {bytes([c]): [b"QQ"] for c in b"abcdef"}
        words = [b"abcdefabcdef", b"fedcbafedcba", b"abc"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.windowed and pe.scalar_units_for(plan)
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo="md5",
        ) > 0
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo="md5",
            scalar_units=False,
        ) > 0

    def test_closed_suball_kernel(self):
        sub = BUILTIN_LAYOUTS["qwerty-azerty"].to_substitution_map()
        spec = AttackSpec(mode="suball", algo="md5")
        words = [b"aqwzsxm,", b"marmalade", b"qqaazz", b"azerty"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None and schema.closed
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo="md5",
            scalar_units=False,
        ) > 0


class TestHierarchicalPlacement:
    """The word-bucketed placement windows (PERF.md §18): per-group
    static [off_floor, off_cap] byte windows bound the scatter, fixed
    groups (``len_fixed``) keep the running offset static, and narrow
    groups move to the u16 ``gw16`` table — all of which must stay
    byte-invisible next to the bytescan twin."""

    def test_variable_length_values_open_windows(self):
        # 1- vs 3-byte options make every selector group's placed length
        # vary, so downstream groups get real (floor < cap) windows.
        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"a": [b"Z", b"XYZ"], b"e": [b"9", b"123"]}
        words = [b"banana-tree", b"elephant", b"weave", b"qqq"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None
        assert any(g.off_floor < g.off_cap for g in schema.groups)
        assert any(g.off_floor == g.off_cap for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)
        assert_pallas_parity(spec, plan, ct, schema, parr, t, b,
                             algo="md5", scalar_units=False)

    @pytest.mark.parametrize("algo", ["md5", "ntlm"])
    def test_all_fixed_groups_collapse_to_static_placement(self, algo):
        # Length-preserving 1:1 values with uniform match geometry:
        # every group's placed length is fixed, so the whole scatter
        # lowers to static shift-ORs (degenerate windows) — including
        # NTLM's split pieces and the terminator-folded tail.
        spec = AttackSpec(mode="default", algo=algo)
        sub = {b"a": [b"4"], b"o": [b"0"], b"s": [b"5"]}
        words = [b"password"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None
        assert all(g.len_fixed is not None for g in schema.groups)
        assert all(g.off_floor == g.off_cap for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo=algo,
        ) > 0

    @pytest.mark.parametrize("algo", ["md5", "ntlm"])
    def test_gw16_carries_short_groups(self, algo):
        # Standalone 4-variant selector columns can't merge with each
        # other (the variant-product cap), so groups stay <= 2 bytes
        # (gap + 1-byte span) and every variant word fits u16 — the
        # whole table moves to gw16.  NTLM pins the utf16 split where
        # the packed16 hi pair is statically zero and elided.
        spec = AttackSpec(mode="default", algo=algo)
        sub = {b"a": [b"X", b"Y", b"Z"]}
        words = [b"banana", b"cabana", b"baobab"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None
        assert schema.gw16 is not None
        assert any(g.packed16 for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)
        assert_pallas_parity(spec, plan, ct, schema, parr, t, b,
                             algo=algo, scalar_units=False)

    @pytest.mark.parametrize("mode,algo", [
        # The NTLM arm's utf16-doubled widths make its interpret-mode
        # Pallas parity super-linear (~54 s alone), and the default-md5
        # arm costs another ~27 s; the suball-md5 arm keeps the
        # window/terminator coverage in the default tier, the NTLM
        # utf16 fold is pinned by the (fast) gw16/terminator tests
        # above, and CI's slow steps still run both marked arms.
        pytest.param("default", "md5", marks=pytest.mark.slow),
        pytest.param("default", "ntlm", marks=pytest.mark.slow),
        ("suball", "md5"),
    ])
    def test_window_fuzz_long_words(self, mode, algo):
        # Seeded fuzz at 2-hash-block-like widths: long words × mixed
        # 1..3-byte values stack many groups, so late groups' windows
        # and the multi-block terminator fold are all exercised.
        # zlib.crc32, not hash(): str hashing is salted per process, and
        # this test makes a seed-dependent structural assertion below.
        rng = np.random.default_rng(
            zlib.crc32(f"win-{mode}-{algo}".encode())
        )
        spec = AttackSpec(mode=mode, algo=algo)
        sub = rand_table(rng, k_opts=2, val_len=3)
        words = [
            bytes(rng.choice(list(b"abcdefgh~!"),
                             size=int(rng.integers(20, 30))).astype(
                np.uint8))
            for _ in range(4)
        ]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        if schema is None:
            pytest.skip("randomized geometry rejected the schema")
        assert any(g.off_floor < g.off_cap for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)
        assert assert_pallas_parity(
            spec, plan, ct, schema, parr, t, b, algo=algo,
            scalar_units=False,
        ) > 0

    def test_suball_fallback_words_do_not_widen_windows(self):
        # A hazard word routed to the oracle has blanked columns (its
        # whole word becomes tail literals); the windows must be
        # computed over LAUNCHED words only, or its full-length tail
        # would stretch every group's cap.
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"qq"]}
        words = [b"za", b"acbacbacbacbacb", b"az"]
        spec = AttackSpec(mode="suball", algo="md5")
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.fallback.any(), "fixture must exercise fallback"
        assert schema is not None
        launched_len = max(
            int(l) for l, fb in zip(plan.lengths, plan.fallback) if not fb
        )
        # The cap can exceed the launched byte budget only by value
        # growth (+1 terminator) — never by the fallback word's length.
        assert schema.max_out <= 2 * launched_len + 1
        assert_xla_parity(spec, plan, schema, parr, t, b)

    def test_suball_fallback_words_do_not_veto_packed16(self):
        # Same masking rule for the u16 gate: the oracle-routed word's
        # 4-byte tail chunks (>= 2^16 as u32 words) sit in groups whose
        # LAUNCHED entries all fit 2 bytes — they must still move to
        # gw16 (the fallback row is never read by a launched lane, so
        # its truncated entry is unobservable).
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"qq"]}
        words = [b"za", b"acbacbacbacbacb", b"az"]
        spec = AttackSpec(mode="suball", algo="md5")
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert plan.fallback.any(), "fixture must exercise fallback"
        assert schema is not None
        assert schema.gw16 is not None
        assert any(g.packed16 for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)


class TestGates:
    def test_env_escape_hatch(self, monkeypatch):
        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"a": [b"X"]}
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words([b"banana"]))
        monkeypatch.setenv("A5GEN_EMIT", "bytescan")
        assert emit_scheme() == "bytescan"
        assert piece_schema_for(plan, ct) is None
        monkeypatch.setenv("A5GEN_EMIT", "perslot")
        assert emit_scheme() == "perslot"
        assert piece_schema_for(plan, ct) is not None

    def test_env_typo_warns_and_keeps_default(self, monkeypatch, capsys):
        monkeypatch.setenv("A5GEN_EMIT", "bytescn")
        assert emit_scheme() == "perslot"
        assert "A5GEN_EMIT" in capsys.readouterr().err

    @pytest.mark.slow  # ~7 s interpret cost on the tier-1 host; the
    # bucket-word tail chunking keeps default coverage via the bucketed
    # sweep parity tests in test_bucketed.
    def test_matchless_bucket_word_chunks_its_tail(self):
        # A 16-byte word with no matches must not veto the schema: its
        # tail splits into <=4-byte literal chunk groups instead of one
        # over-wide piece (the production bucket-16 case).
        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"a": [b"X"], b"e": [b"3"]}
        words = [b"zzzzzzzzzzzzzzzz", b"banana", b"eeeaaa"]
        ct, plan, schema, parr, t, b = _setup(spec, sub, words)
        assert schema is not None
        assert all(g.n_words == 1 for g in schema.groups)
        assert_xla_parity(spec, plan, schema, parr, t, b)
        assert_pallas_parity(spec, plan, ct, schema, parr, t, b,
                             algo="md5")

    def test_schema_refuses_overlapping_static_spans(self):
        # Keys "ab" and "b": matches at (0, len 2) and (1, len 1) overlap
        # STATICALLY — piece emission cannot express the skip geometry,
        # so the gate must return None (bytescan carries the plan).
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table({b"ab": [b"X"], b"b": [b"Y"]})
        plan = build_plan(spec, ct, pack_words([b"abab"]))
        assert piece_schema_for(plan, ct) is None

    def test_schema_cache_keyed_by_table(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table({b"a": [b"X"]})
        plan = build_plan(spec, ct, pack_words([b"banana"]))
        s1 = piece_schema_for(plan, ct)
        assert piece_schema_for(plan, ct) is s1  # cached

    def test_builder_rejects_unsorted_spans(self):
        tokens = np.zeros((1, 8), np.uint8)
        lengths = np.full((1,), 8, np.int32)
        pos = np.asarray([[4, 1]], np.int32)  # descending -> refuse
        ln = np.asarray([[1, 1]], np.int32)
        opts = np.asarray([[1, 1]], np.int32)
        vstart = np.zeros((1, 2), np.int32)
        vb = np.zeros((1, 2), np.uint8)
        vl = np.ones((1,), np.int32)
        assert build_piece_schema(
            tokens, lengths, pos, ln, opts, vstart, vb, vl, kind="match",
        ) is None

"""Length-bucketed sweeps: bucket assignment parity (numpy vs native),
per-bucket compiled widths (one long line must not inflate every lane —
VERDICT r1 weak #6), multiset parity, global hit indices, per-bucket
checkpoints, and the CLI --buckets surface."""

import hashlib
import io
from collections import Counter

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu import native
from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.ops.packing import (
    bucket_words,
    pack_words,
)
from hashcat_a5_table_generator_tpu.runtime import (
    BucketedSweep,
    CandidateWriter,
    HitRecorder,
    SweepConfig,
)

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
#: Mixed lengths spanning three buckets plus an over-the-last-boundary
#: outlier that lands in a power-of-two bucket of its own (128).  Compile
#: cost scales with width, so the jit tests keep the outlier modest; the
#: pure width-assignment math is separately checked at 300 bytes below.
WORDS = [
    b"password",                      # 8  -> bucket 16
    b"q" * 20 + b"so",                # 22 -> bucket 32 ('q' never matches)
    b"zzz",                           # 3  -> bucket 16
    b"x" * 40 + b"ae",                # 42 -> bucket 64
    b"q" * 68 + b"as",                # 70 -> power-of-two bucket 128
    b"sesame",                        # 6  -> bucket 16
]


def oracle_lines(spec, sub_map, words):
    out = []
    for w in words:
        out.extend(
            iter_candidates(
                w, sub_map, spec.min_substitute, spec.max_substitute,
                substitute_all=spec.mode.startswith("suball"),
                reverse=spec.mode in ("reverse", "suball-reverse"),
            )
        )
    return out


class TestBucketAssignment:
    def test_native_widths_match_numpy_bucketing(self):
        lengths = np.asarray([len(w) for w in WORDS])
        widths = native.bucket_widths(lengths)
        by_np = bucket_words(WORDS)
        want = {}
        for width, packed in by_np.items():
            for i in packed.index:
                want[int(i)] = width
        assert [want[i] for i in range(len(WORDS))] == [int(w) for w in widths]
        assert sorted(set(int(w) for w in widths)) == [16, 32, 64, 128]
        # Pure math check for a rockyou-style 300-byte outlier (no jit).
        assert int(native.bucket_widths(np.asarray([300]))[0]) == 512

    def test_read_packed_buckets_matches_bucket_words(self, tmp_path):
        path = tmp_path / "dict.txt"
        path.write_bytes(b"\n".join(WORDS) + b"\n")
        got = native.read_packed_buckets(str(path))
        want = bucket_words(WORDS)
        assert sorted(got) == sorted(want)
        for width in want:
            assert got[width].tokens.shape == want[width].tokens.shape
            np.testing.assert_array_equal(got[width].tokens,
                                          want[width].tokens)
            np.testing.assert_array_equal(got[width].lengths,
                                          want[width].lengths)
            np.testing.assert_array_equal(got[width].index,
                                          want[width].index)

    def test_empty_file(self, tmp_path):
        path = tmp_path / "empty.txt"
        path.write_bytes(b"")
        assert native.read_packed_buckets(str(path)) == {}


class TestBucketedSweep:
    def test_per_bucket_out_width_not_global_max(self):
        # The whole point: the 300-byte outlier may not inflate the short
        # words' compiled width.
        spec = AttackSpec(mode="default", algo="md5")
        bs = BucketedSweep(
            spec, LEET, bucket_words(WORDS),
            config=SweepConfig(lanes=256, num_blocks=32),
        )
        assert sorted(bs.sweeps) == [16, 32, 64, 128]
        global_width = pack_words(WORDS).width  # 300 rounded up
        for width, sweep in bs.sweeps.items():
            assert sweep.packed.width == width
            assert sweep.plan.out_width < global_width or width == 128
        assert bs.sweeps[16].plan.out_width <= 32  # 16 + expansion margin

    # Auto resolves to stride here (backend-independent rule, PERF.md
    # §4c); layout=True keeps bucketed sweeps' packed-layout coverage.
    @pytest.mark.parametrize("layout", [None, True], ids=["auto", "packed"])
    def test_candidates_multiset_matches_oracle(self, layout):
        spec = AttackSpec(mode="default", algo="md5")
        bs = BucketedSweep(
            spec, LEET, bucket_words(WORDS),
            config=SweepConfig(lanes=256, num_blocks=32,
                               packed_blocks=layout),
        )
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            res = bs.run_candidates(w)
        want = oracle_lines(spec, LEET, WORDS)
        assert Counter(buf.getvalue().splitlines()) == Counter(want)
        assert res.n_emitted == len(want)
        assert res.words_done == len(WORDS)

    @pytest.mark.slow  # ~11 s on the tier-1 host; global-position
    # mapping keeps default coverage via the bucketed sweep parity
    # arms above.
    def test_crack_hits_report_global_dictionary_positions(self):
        spec = AttackSpec(mode="default", algo="md5")
        # Plant one hit in the 16-bucket and one in the 128-bucket.
        short_cand = oracle_lines(spec, LEET, [WORDS[5]])[-1]   # sesame row 5
        long_cand = oracle_lines(spec, LEET, [WORDS[4]])[0]     # 70-byte row
        digests = [hashlib.md5(short_cand).digest(),
                   hashlib.md5(long_cand).digest()]
        bs = BucketedSweep(
            spec, LEET, bucket_words(WORDS), digests,
            config=SweepConfig(lanes=256, num_blocks=32),
        )
        rec = HitRecorder()
        res = bs.run_crack(rec)
        # Result hits are globally sorted by dictionary position.
        assert [(h.word_index, h.candidate) for h in res.hits] == [
            (4, long_cand), (5, short_cand),
        ]
        # The streaming recorder saw the same hits (bucket-major order).
        assert {(h.word_index, h.candidate) for h in rec.hits} == {
            (4, long_cand), (5, short_cand),
        }
        assert res.n_emitted == len(oracle_lines(spec, LEET, WORDS))

    def test_per_bucket_checkpoints_resume(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        ck = str(tmp_path / "bk.json")
        cfg = SweepConfig(lanes=256, num_blocks=32, checkpoint_path=ck,
                          checkpoint_every_s=0.0)
        buckets = bucket_words(WORDS)
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            BucketedSweep(spec, LEET, buckets, config=cfg).run_candidates(w)
        assert buf.getvalue()
        for width in buckets:
            assert (tmp_path / f"bk.json.w{width}").exists()
        # Every bucket's checkpoint is complete: resume emits nothing.
        buf2 = io.BytesIO()
        with CandidateWriter(buf2) as w2:
            res = BucketedSweep(
                spec, LEET, buckets, config=cfg
            ).run_candidates(w2)
        assert res.resumed
        assert buf2.getvalue() == b""

    def test_single_bucket_stream_identical_to_unbucketed(self):
        from hashcat_a5_table_generator_tpu.runtime import Sweep

        spec = AttackSpec(mode="default", algo="md5")
        short = [w for w in WORDS if len(w) <= 16]
        cfg = SweepConfig(lanes=256, num_blocks=32)

        buf_b = io.BytesIO()
        with CandidateWriter(buf_b) as w:
            BucketedSweep(
                spec, LEET, bucket_words(short), config=cfg
            ).run_candidates(w)
        buf_s = io.BytesIO()
        with CandidateWriter(buf_s) as w:
            Sweep(spec, LEET, short, config=cfg).run_candidates(w)
        assert buf_b.getvalue() == buf_s.getvalue()


class TestBucketManifest:
    """--checkpoint FILE under bucketing writes a top-level manifest at
    FILE (VERDICT r2 weak #2) and refuses legacy/mismatched files
    (ADVICE r2: a pre-manifest single-file checkpoint must not be
    silently ignored)."""

    def _cfg(self, tmp_path, **kw):
        return SweepConfig(lanes=256, num_blocks=32,
                           checkpoint_path=str(tmp_path / "ck.json"),
                           checkpoint_every_s=0.0, **kw)

    def test_manifest_written_at_checkpoint_path(self, tmp_path):
        import json

        spec = AttackSpec(mode="default", algo="md5")
        cfg = self._cfg(tmp_path)
        buckets = bucket_words(WORDS)
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            BucketedSweep(spec, LEET, buckets, config=cfg).run_candidates(w)
        doc = json.loads((tmp_path / "ck.json").read_text())
        assert doc["kind"] == "bucket-manifest"
        widths = {int(k) for k in doc["buckets"]}
        assert widths == {w for w, p in buckets.items() if p.batch}
        for wd, entry in doc["buckets"].items():
            assert (tmp_path / entry["file"]).exists()

    def test_legacy_single_file_checkpoint_rejected(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        cfg = self._cfg(tmp_path)
        # A pre-manifest layout: single-sweep checkpoint at the bare path.
        from hashcat_a5_table_generator_tpu.runtime import Sweep

        Sweep(spec, LEET, [b"zzz"],
              config=SweepConfig(lanes=256, num_blocks=32,
                                 checkpoint_path=str(tmp_path / "ck.json"))
              ).run_candidates(CandidateWriter(io.BytesIO()))
        bs = BucketedSweep(spec, LEET, bucket_words(WORDS), config=cfg)
        with pytest.raises(ValueError, match="single-sweep checkpoint"):
            bs.run_candidates(CandidateWriter(io.BytesIO()))

    def test_manifest_rejected_by_unbucketed_sweep(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        cfg = self._cfg(tmp_path)
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            BucketedSweep(spec, LEET, bucket_words(WORDS),
                          config=cfg).run_candidates(w)
        from hashcat_a5_table_generator_tpu.runtime import Sweep

        sweep = Sweep(spec, LEET, WORDS,
                      config=SweepConfig(
                          lanes=256, num_blocks=32,
                          checkpoint_path=str(tmp_path / "ck.json")))
        with pytest.raises(ValueError, match="bucket manifest"):
            sweep.run_candidates(CandidateWriter(io.BytesIO()))

    def test_resume_with_different_buckets_rejected(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        cfg = self._cfg(tmp_path)
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            BucketedSweep(spec, LEET, bucket_words(WORDS),
                          config=cfg).run_candidates(w)
        other = BucketedSweep(
            spec, LEET, bucket_words(WORDS, buckets=(32, 64)), config=cfg
        )
        with pytest.raises(ValueError, match="different"):
            other.run_candidates(CandidateWriter(io.BytesIO()))

    def test_no_resume_overwrites_manifest(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        cfg = self._cfg(tmp_path)
        bucket_sets = bucket_words(WORDS)
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            BucketedSweep(spec, LEET, bucket_sets,
                          config=cfg).run_candidates(w)
        # Different bucket layout + resume=False: manifest is replaced.
        other = BucketedSweep(
            spec, LEET, bucket_words(WORDS, buckets=(32, 64)), config=cfg
        )
        buf2 = io.BytesIO()
        with CandidateWriter(buf2) as w2:
            other.run_candidates(w2, resume=False)
        assert sorted(buf2.getvalue().splitlines()) == sorted(
            buf.getvalue().splitlines()
        )


class TestUnsortedBuckets:
    """Both width-assignment paths reject unsorted bucket tuples rather
    than diverging (native sorted internally, Python first-matched in
    caller order — advisor r2)."""

    def test_bucket_words_rejects_unsorted(self):
        with pytest.raises(ValueError, match="ascending"):
            bucket_words([b"abc"], buckets=(64, 16))

    def test_native_bucket_widths_rejects_unsorted(self):
        with pytest.raises(ValueError, match="ascending"):
            native.bucket_widths(np.asarray([3]), buckets=(64, 16))

    def test_paths_agree_on_valid_tuples(self):
        lengths = [1, 5, 16, 17, 33, 70, 300]
        words = [b"x" * n for n in lengths]
        buckets = (16, 24, 48)
        by_python = bucket_words(words, buckets=buckets,
                                 max_word_bytes=1024)
        widths_native = native.bucket_widths(np.asarray(lengths), buckets)
        py_assign = {}
        for width, packed in by_python.items():
            for i in packed.index:
                py_assign[int(i)] = width
        assert [py_assign[i] for i in range(len(words))] == [
            int(w) for w in widths_native
        ]

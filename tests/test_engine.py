"""Resident engine service mode (PERF.md §20): a solo job through the
engine must be byte-identical to ``run_crack``/``run_candidates`` (the
engine runs the SAME machine those paths exhaust — these tests pin it),
multiplexed jobs keep per-job hit attribution, pause/resume/cancel ride
``CheckpointState`` across engine instances, warm jobs share compiled
programs (the compile-once seam), and the schema cache reports hygiene
counters and honors its LRU cap.  Plus the JSONL service front-end and
the ``--serve-ab`` bench record shape (slow-marked: subprocess bench).

Tier-1 budget: fast tests share the test suite's 64-lane × 16-block
geometry so the process step cache serves them all; the heavier mode
variants are slow-marked per the 870 s contract.
"""

import hashlib
import io
import json
import pathlib
import subprocess
import sys
import time

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import (
    CandidateWriter,
    Sweep,
    SweepConfig,
)
from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
    CheckpointState,
    SweepCursor,
    state_from_doc,
    state_to_doc,
)
from hashcat_a5_table_generator_tpu.runtime.engine import (
    Engine,
    JobFailed,
    serve_stdio,
)
from tests.test_superstep import LEET, WORDS, hit_tuples, oracle_lines

REPO = pathlib.Path(__file__).resolve().parent.parent

LONG_WORDS = WORDS * 4  # spans several 64-lane supersteps at superstep=1


def cfg(**kw):
    return SweepConfig(lanes=64, num_blocks=16, **kw)


def planted_digests(spec, sub_map, words, picks=(0, -1), decoys=20):
    oracle = oracle_lines(spec, sub_map, words)
    planted = sorted({oracle[i] for i in picks})
    digests = [hashlib.md5(c).digest() for c in planted]
    digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(decoys)]
    return planted, digests


def full_hits(res):
    """Ordered full-record tuples: the byte-exact stream comparison."""
    return [
        (h.word_index, h.variant_rank, h.candidate, h.digest_hex)
        for h in res.hits
    ]


class TestSoloParity:
    """engine == run_crack / run_candidates, bit for bit: the engine
    exhausts the identical machine, so a solo job cannot drift."""

    @pytest.mark.parametrize("mode", [
        "default", pytest.param("suball", marks=pytest.mark.slow),
    ])
    def test_crack_parity(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        _planted, digests = planted_digests(spec, LEET, WORDS, (0, 7, -1))
        want = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        eng = Engine(cfg(), auto=False)
        job = eng.submit(spec, LEET, WORDS, digests)
        eng.run_until_idle()
        got = job.result(timeout=0)
        assert full_hits(got) == full_hits(want)
        assert got.n_emitted == want.n_emitted
        assert got.routing == want.routing

    @pytest.mark.slow  # ~9 s on the tier-1 host; streaming solo parity
    # keeps default coverage via test_streaming's stream-parity arms
    # and the packed streaming survivors in test_pack.
    def test_streaming_crack_parity(self):
        spec = AttackSpec(mode="default", algo="md5")
        _planted, digests = planted_digests(spec, LEET, WORDS, (0, -1))
        c = cfg(stream_chunk_words=2)
        want = Sweep(spec, LEET, WORDS, digests, config=c).run_crack()
        assert want.stream["chunks_swept"] == 3
        eng = Engine(c, auto=False)
        job = eng.submit(spec, LEET, WORDS, digests)
        eng.run_until_idle()
        got = job.result(timeout=0)
        assert full_hits(got) == full_hits(want)
        assert got.n_emitted == want.n_emitted
        assert got.stream["chunks_swept"] == 3

    def test_candidates_byte_parity(self):
        spec = AttackSpec(mode="default", algo="md5")
        want = io.BytesIO()
        with CandidateWriter(stream=want) as w:
            Sweep(spec, LEET, WORDS, config=cfg()).run_candidates(w)
        got = io.BytesIO()
        eng = Engine(cfg(), auto=False)
        job = eng.submit(spec, LEET, WORDS, kind="candidates",
                         writer=CandidateWriter(stream=got))
        eng.run_until_idle()
        res = job.result(timeout=0)
        job._submit_args["writer"].close()
        assert got.getvalue() == want.getvalue()
        assert res.n_emitted > 0

    @pytest.mark.slow
    def test_windowed_crack_parity(self):
        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=1)
        _planted, digests = planted_digests(spec, LEET, WORDS, (0, -1))
        sweep = Sweep(spec, LEET, WORDS, digests, config=cfg())
        assert sweep.plan.windowed
        want = sweep.run_crack()
        eng = Engine(cfg(), auto=False)
        job = eng.submit(spec, LEET, WORDS, digests)
        eng.run_until_idle()
        assert full_hits(job.result(timeout=0)) == full_hits(want)


class TestMultiplexing:
    def test_two_job_packed_superstep_parity(self):
        """Two jobs interleave at superstep boundaries on one shared
        compiled program; each job's hit stream is exactly its solo
        run's — per-job (word, rank) attribution never crosses jobs.
        (words2 is a permutation of job 1's dictionary: equal batch
        shapes land both jobs on ONE compiled executable — the packed
        case the scheduler groups for.)"""
        spec = AttackSpec(mode="default", algo="md5")
        _p1, digests1 = planted_digests(spec, LEET, LONG_WORDS, (0, 5))
        words2 = LONG_WORDS[::-1]
        _p2, digests2 = planted_digests(spec, LEET, words2, (1, -1))
        c = cfg(superstep=1)
        want1 = Sweep(spec, LEET, LONG_WORDS, digests1,
                      config=c).run_crack()
        want2 = Sweep(spec, LEET, words2, digests2, config=c).run_crack()

        eng = Engine(c, auto=False)
        j1 = eng.submit(spec, LEET, LONG_WORDS, digests1)
        j2 = eng.submit(spec, LEET, words2, digests2)
        eng._admit()
        assert len(eng._active) == 2
        # Both jobs still running after one round each = interleaved.
        eng._serve_round()
        assert j1.state == "running" and j2.state == "running"
        assert len({s.group for s in eng._active}) == 1  # packed group
        eng.run_until_idle()
        assert full_hits(j1.result(timeout=0)) == full_hits(want1)
        assert full_hits(j2.result(timeout=0)) == full_hits(want2)
        assert j1.result(0).n_emitted == want1.n_emitted
        assert j2.result(0).n_emitted == want2.n_emitted

    def test_warm_jobs_compile_no_new_programs(self):
        """The compile-amortization claim: after one job of a config
        has run, further equal jobs build ZERO new programs — they ride
        the process step cache (N jobs, one program build)."""
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0,))
        # pack=False: this pins the PER-JOB dispatch path's amortization
        # (a packed batch adds exactly one fused program on first use —
        # its own compile-once claim lives in test_pack.py).
        eng = Engine(cfg(), auto=False, pack=False)
        first = eng.submit(spec, LEET, WORDS, digests)
        eng.run_until_idle()
        first.result(timeout=0)
        compiled_after_first = eng.stats()["programs_compiled"]
        jobs = [eng.submit(spec, LEET, WORDS, digests) for _ in range(3)]
        eng.run_until_idle()
        for j in jobs:
            assert j.result(timeout=0).n_hits == first.result(0).n_hits
        stats = eng.stats()
        assert stats["programs_compiled"] == compiled_after_first
        assert stats["program_cache_hits"] > 0
        assert stats["jobs_done"] == 4

    def test_async_hit_delivery(self):
        """Hits stream through the bounded per-job queue as the
        once-per-superstep fetch lands them, not at job end."""
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0, 3, -1))
        eng = Engine(cfg())  # auto serve thread
        try:
            job = eng.submit(spec, LEET, WORDS, digests)
            got = list(job.iter_hits())  # drains until the job settles
            res = job.result(timeout=30)
            assert [
                (h.word_index, h.variant_rank) for h in got
            ] == [(h.word_index, h.variant_rank) for h in res.hits]
            assert len(got) == res.n_hits > 0
        finally:
            eng.close()


class TestTenantOps:
    def test_pause_checkpoint_resume_on_second_engine(self):
        """Pause parks the job at a fetched superstep boundary and its
        CheckpointState resumes on a DIFFERENT engine to the identical
        final stream — a migrating job is just a checkpoint."""
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, LONG_WORDS, (0, 5, -1))
        c = cfg(superstep=1)
        want = Sweep(spec, LEET, LONG_WORDS, digests, config=c).run_crack()

        eng_a = Engine(c, auto=False)
        job = eng_a.submit(spec, LEET, LONG_WORDS, digests)
        eng_a._admit()
        eng_a._serve_round()
        eng_a._serve_round()
        job.request_pause()
        eng_a._serve_round()
        assert job.state == "paused"
        ck = job.checkpoint
        assert ck is not None
        assert (ck.cursor.word, ck.cursor.rank) > (0, 0)
        assert ck.cursor.word < len(LONG_WORDS)  # genuinely mid-sweep

        eng_b = Engine(c, auto=False)
        job2 = eng_b.submit(spec, LEET, LONG_WORDS, digests,
                            resume_state=ck)
        eng_b.run_until_idle()
        got = job2.result(timeout=0)
        assert got.resumed
        assert full_hits(got) == full_hits(want)
        assert got.n_emitted == want.n_emitted

    def test_pause_round_trips_through_json(self):
        """The JSONL pause/migrate wire format: state_to_doc/state_from_doc
        survive json encoding, including >2^63 variant ranks."""
        state = CheckpointState(
            fingerprint="fp", cursor=SweepCursor(3, 10**25),
            n_emitted=7, n_hits=1, hits=[(2, 10**24)], fallback_done=1,
            wall_s=0.5, stream={"chunk": 2, "chunk_words": 4},
        )
        doc = json.loads(json.dumps(state_to_doc(state)))
        assert state_from_doc(doc) == state

    def test_resume_same_engine(self):
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, LONG_WORDS, (0, -1))
        c = cfg(superstep=1)
        want = Sweep(spec, LEET, LONG_WORDS, digests, config=c).run_crack()
        eng = Engine(c, auto=False)
        job = eng.submit(spec, LEET, LONG_WORDS, digests)
        eng._admit()
        eng._serve_round()
        job.request_pause()
        eng._serve_round()
        assert job.state == "paused"
        job2 = eng.resume(job)
        assert job2.id == job.id
        eng.run_until_idle()
        assert full_hits(job2.result(timeout=0)) == full_hits(want)

    def test_cancel_mid_superstep_keeps_other_tenants(self):
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, LONG_WORDS, (0, -1))
        c = cfg(superstep=1)
        want_other = Sweep(spec, LEET, WORDS, digests, config=c).run_crack()
        eng = Engine(c, auto=False)
        victim = eng.submit(spec, LEET, LONG_WORDS, digests)
        other = eng.submit(spec, LEET, WORDS, digests)
        eng._admit()
        eng._serve_round()
        assert victim.state == "running"
        victim.cancel()
        eng.run_until_idle()
        assert victim.state == "cancelled"
        with pytest.raises(Exception):
            victim.result(timeout=0)
        assert full_hits(other.result(timeout=0)) == full_hits(want_other)
        assert eng.stats()["jobs_cancelled"] == 1

    def test_pause_before_first_tick_hands_back_origin_checkpoint(self):
        """Pausing a job whose machine never ticked still yields a
        RESUMABLE checkpoint — the start of the sweep, never None."""
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0, -1))
        want = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        eng = Engine(cfg(), auto=False)
        job = eng.submit(spec, LEET, WORDS, digests)
        eng._admit()
        job.request_pause()
        eng._serve_round()  # parks before any machine tick
        assert job.state == "paused"
        ck = job.checkpoint
        assert ck is not None
        assert (ck.cursor.word, ck.cursor.rank) == (0, 0)
        json.dumps(state_to_doc(ck))  # the JSONL pump must not crash
        eng2 = Engine(cfg(), auto=False)
        job2 = eng2.submit(spec, LEET, WORDS, digests, resume_state=ck)
        eng2.run_until_idle()
        assert full_hits(job2.result(timeout=0)) == full_hits(want)

    def test_resume_fingerprint_mismatch_fails_loudly(self):
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0,))
        eng = Engine(cfg(), auto=False)
        bad = CheckpointState(fingerprint="not-this-sweep")
        job = eng.submit(spec, LEET, WORDS, digests, resume_state=bad)
        eng.run_until_idle()
        assert job.state == "failed"
        with pytest.raises(JobFailed) as exc:
            job.result(timeout=0)
        assert "different sweep" in str(exc.value.__cause__)


class TestSchemaCacheHygiene:
    def test_counters_surface_in_sweep_result(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0,))
        c = cfg(schema_cache=str(tmp_path))
        first = Sweep(spec, LEET, WORDS, digests, config=c).run_crack()
        assert first.schema_cache.get("misses", 0) >= 1
        assert first.schema_cache.get("bytes_written", 0) > 0
        second = Sweep(spec, LEET, WORDS, digests, config=c).run_crack()
        assert second.schema_cache.get("hits", 0) >= 1
        assert second.schema_cache.get("bytes_read", 0) > 0
        assert second.schema_cache.get("misses", 0) == 0
        assert hit_tuples(second) == hit_tuples(first)

    def test_lru_cap_evicts_oldest_atime(self, tmp_path):
        from hashcat_a5_table_generator_tpu.ops.packing import (
            enforce_schema_cache_cap,
            schema_cache_stats,
        )

        paths = []
        for i in range(4):
            p = tmp_path / f"entry{i}.npz"
            p.write_bytes(bytes(1 << 20))  # 1 MB each
            paths.append(p)
        now = time.time()
        for i, p in enumerate(paths):  # entry0 oldest atime
            import os

            os.utime(p, (now - 1000 + i * 100, now))
        before = schema_cache_stats()
        evicted = enforce_schema_cache_cap(str(tmp_path), max_mb=2.5)
        assert evicted == 2
        assert not paths[0].exists() and not paths[1].exists()
        assert paths[2].exists() and paths[3].exists()
        after = schema_cache_stats()
        assert after["evictions"] - before.get("evictions", 0) == 2
        # Under the cap: a no-op.
        assert enforce_schema_cache_cap(str(tmp_path), max_mb=2.5) == 0

    def test_corrupt_entry_still_a_miss_after_counters(self, tmp_path):
        """Counter instrumentation must not change the corrupt-entry
        contract: a garbage file is a MISS, never an error."""
        from hashcat_a5_table_generator_tpu.ops.packing import (
            load_piece_schema,
            schema_cache_stats,
        )

        (tmp_path / "deadbeef.npz").write_bytes(b"not an npz at all")
        before = schema_cache_stats()
        hit, schema = load_piece_schema(str(tmp_path), "deadbeef")
        assert (hit, schema) == (False, None)
        assert schema_cache_stats()["misses"] - before["misses"] == 1

    def test_engine_stats_report_schema_cache(self, tmp_path):
        spec = AttackSpec(mode="default", algo="md5")
        _p, digests = planted_digests(spec, LEET, WORDS, (0,))
        c = cfg(schema_cache=str(tmp_path))
        eng = Engine(c, auto=False)
        for _ in range(2):
            eng.submit(spec, LEET, WORDS, digests)
        eng.run_until_idle()
        sc = eng.stats()["schema_cache"]
        assert sc.get("misses", 0) >= 1  # first job compiled + wrote
        assert sc.get("hits", 0) >= 1  # second job loaded


class TestJsonlService:
    def test_stdin_session_submit_hit_done(self):
        # Same words/digest-count fixture as the solo parity test, so
        # the session rides the executables this suite already built.
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, LEET, WORDS, (3,),
                                           decoys=22)
        dig = hashlib.md5(planted[0]).digest()
        eng = Engine(cfg())
        try:
            reqs = io.StringIO(
                json.dumps({
                    "op": "submit", "id": "t1",
                    "words": [w.decode() for w in WORDS],
                    "table_map": {"a": ["4", "@"], "o": ["0"],
                                  "s": ["$", "5"], "e": ["3"]},
                    "algo": "md5",
                    "digest_list": [d.hex() for d in digests],
                }) + "\n" + json.dumps({"op": "stats"}) + "\n"
                + json.dumps({"op": "shutdown"}) + "\n"
            )
            out = io.StringIO()
            serve_stdio(eng, reqs, out)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if '"done"' in out.getvalue():
                    break
                time.sleep(0.05)
            events = [json.loads(ln) for ln in
                      out.getvalue().splitlines() if ln.strip()]
            by_event = {}
            for e in events:
                by_event.setdefault(e["event"], []).append(e)
            assert by_event["accepted"][0]["id"] == "t1"
            (hit,) = by_event["hit"]
            assert hit["digest"] == dig.hex()
            assert bytes.fromhex(hit["plain_hex"]) == planted[0]
            (done,) = by_event["done"]
            assert done["n_hits"] == 1 and done["n_emitted"] > 0
            assert "jobs_submitted" in by_event["stats"][0]
            assert by_event["bye"]
        finally:
            eng.close()

    def test_bad_job_reports_error_and_keeps_session(self):
        eng = Engine(cfg())
        try:
            reqs = io.StringIO(
                '{"op":"submit","id":"x"}\n'
                '{"op":"nope","id":"x"}\n{"op":"shutdown"}\n'
            )
            out = io.StringIO()
            serve_stdio(eng, reqs, out)
            events = [json.loads(ln) for ln in
                      out.getvalue().splitlines() if ln.strip()]
            assert [e["event"] for e in events] == ["error", "error", "bye"]
        finally:
            eng.close()

    @pytest.mark.slow
    def test_unix_socket_session(self, tmp_path):
        import socket
        import threading

        from hashcat_a5_table_generator_tpu.runtime.engine import (
            serve_socket,
        )

        spec = AttackSpec(mode="default", algo="md5")
        planted, _d = planted_digests(spec, LEET, [b"password"], (3,))
        dig = hashlib.md5(planted[0]).digest()
        path = str(tmp_path / "a5.sock")
        eng = Engine(cfg())
        ready = threading.Event()
        th = threading.Thread(
            target=serve_socket, args=(eng, path),
            kwargs={"ready": ready.set}, daemon=True,
        )
        th.start()
        try:
            assert ready.wait(10)
            # A client that merely disconnects (a health probe) must
            # end only ITS session, not the server.
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.connect(path)
            probe.close()
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(path)
                fin = s.makefile("r", encoding="utf-8")
                fout = s.makefile("w", encoding="utf-8")
                fout.write(json.dumps({
                    "op": "submit", "id": "s1", "words": ["password"],
                    "table_map": {"a": ["4", "@"], "o": ["0"],
                                  "s": ["$", "5"], "e": ["3"]},
                    "digest_list": [dig.hex()],
                }) + "\n")
                fout.flush()
                got = [json.loads(fin.readline()) for _ in range(3)]
                assert [e["event"] for e in got] == [
                    "accepted", "hit", "done",
                ]
                fout.write('{"op":"shutdown"}\n')
                fout.flush()
            th.join(10)
        finally:
            eng.close()


@pytest.mark.slow
def test_bench_serve_ab_record_shape():
    """The §20 measurement instrument: one JSON line, both arms, the
    ttfc/wall/compile-count numbers the acceptance criteria read —
    including the compile-once assertion (engine arm builds fewer
    programs than the N-cold-runs arm).  Slow-marked: it compiles and
    times a subprocess bench."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--serve-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "600", "--serve-jobs", "3"],
        capture_output=True, timeout=540, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_mode_ab"
    assert rec["jobs"] == 3
    assert len(rec["cold"]["jobs"]) == 3
    assert len(rec["engine"]["jobs"]) == 4  # + the idle warm probe job
    emitted = {j["n_emitted"] for j in rec["cold"]["jobs"]}
    emitted |= {j["n_emitted"] for j in rec["engine"]["jobs"]}
    assert len(emitted) == 1 and emitted.pop() > 0
    # The compile-once assertion: one resident program build serves
    # every job; the cold arm rebuilds per job.
    assert rec["engine"]["programs_compiled"] < rec["cold"][
        "programs_compiled"
    ]
    assert rec["cold"]["programs_compiled"] >= 3
    assert rec["engine"]["program_cache_hits"] > 0
    for key in ("warm_ttfc_ratio", "warm_ttfc_batch_ratio",
                "wall_ratio", "compile_ratio"):
        assert rec[key] > 0
    assert rec["engine"]["ttfc_warm_idle_s"] < rec["cold"]["ttfc_mean_s"]

"""Runtime compile-cache analyzer (tools/graftlint/runtime.py) around the
hot expand->hash->match path: the production sweep launches ONE compiled
program per (geometry, config), so any per-launch recompilation is a
cache-busting argument signature — on TPU a multi-second stall every
launch.  The static rules (GL006) catch the shapes of this bug; this is
the runtime gate that catches the event itself."""

import hashlib

import jax
import jax.numpy as jnp
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    digest_arrays,
    make_crack_step,
    plan_arrays,
    table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

LEET = {b"a": [b"4", b"@"], b"s": [b"5", b"$"], b"o": [b"0"], b"e": [b"3"]}
WORDS = [b"password", b"assassin", b"glasses"]
STRIDE = 128
NB = 4  # blocks per launch -> 512 lanes


def _fixed_stride_batches(plan, min_batches=2):
    """Cut the keyspace into >= min_batches same-shape launches (the
    production fixed-stride TPU geometry: padded to NB blocks)."""
    batches = []
    w = rank = 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=NB * STRIDE,
            max_blocks=NB, fixed_stride=STRIDE,
        )
        if batch.total == 0:
            break
        batches.append(pad_batch(batch, NB))
    assert len(batches) >= min_batches, "keyspace too small for the test"
    return batches


class TestHotPathCacheStability:
    def test_crack_step_compiles_once_across_launches(self, compile_watcher):
        """Launch-to-launch, only block VALUES change — the compiled
        program must be reused (zero new cache entries after warmup)."""
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        plan = build_plan(spec, ct, pack_words(WORDS))
        ds = build_digest_set(
            [hashlib.md5(b"decoy").digest()], spec.algo
        )
        step = make_crack_step(
            spec, num_lanes=NB * STRIDE, out_width=plan.out_width,
            block_stride=STRIDE,
        )
        p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
        batches = _fixed_stride_batches(plan)

        watcher = compile_watcher(step)
        # Warmup launch: exactly one trace+compile for the whole step.
        with watcher.expect(1, label="warmup"):
            int(step(p, t, block_arrays(batches[0]), d)["n_emitted"])
        # Every further launch: same signature, zero compiles.
        with watcher.expect(0, label="steady-state launches"):
            for batch in batches[1:]:
                int(step(p, t, block_arrays(batch), d)["n_emitted"])

    def test_digest_set_swap_does_not_recompile(self, compile_watcher):
        """Re-targeting (new digest values, same digest-set geometry)
        must not recompile — the sweep reuses the step across target
        reloads."""
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        plan = build_plan(spec, ct, pack_words(WORDS))
        step = make_crack_step(
            spec, num_lanes=NB * STRIDE, out_width=plan.out_width,
            block_stride=STRIDE,
        )
        p, t = plan_arrays(plan), table_arrays(ct)
        blocks = block_arrays(_fixed_stride_batches(plan)[0])

        d1 = digest_arrays(build_digest_set(
            [hashlib.md5(b"one").digest()], spec.algo))
        d2 = digest_arrays(build_digest_set(
            [hashlib.md5(b"two").digest()], spec.algo))
        watcher = compile_watcher(step)
        int(step(p, t, blocks, d1)["n_emitted"])  # warmup
        with watcher.expect(0, label="digest swap"):
            int(step(p, t, blocks, d2)["n_emitted"])


class TestWatcherSelfCheck:
    """The analyzer itself must detect misses, or the guards above are
    vacuous."""

    def test_detects_shape_bust(self, compile_watcher):
        f = jax.jit(lambda x: x * 2)
        watcher = compile_watcher(f)
        f(jnp.ones((4,), jnp.int32)).block_until_ready()
        with pytest.raises(AssertionError, match="cache-busting"):
            with watcher.expect(0):
                # New shape: a fresh signature-cache entry.
                f(jnp.ones((5,), jnp.int32)).block_until_ready()

    def test_counts_warmup_compile(self, compile_watcher):
        f = jax.jit(lambda x: x + 1)
        watcher = compile_watcher(f)
        with watcher.expect(1):
            f(jnp.ones((3,), jnp.int32)).block_until_ready()
        assert watcher.new_entries() == 1

    def test_cache_hit_is_silent(self, compile_watcher):
        f = jax.jit(lambda x: x - 1)
        watcher = compile_watcher(f)
        f(jnp.ones((2,), jnp.int32)).block_until_ready()
        with watcher.expect(0):
            f(jnp.ones((2,), jnp.int32) * 7).block_until_ready()


# ---------------------------------------------------------------------------
# Shared schema cache, concurrent-writer safe (PERF.md §25)
# ---------------------------------------------------------------------------
#
# N fleet engines share ONE --schema-cache directory as the fleet
# artifact store; entries are written through the durable atomic
# replace (checkpoint.atomic_write_bytes), so a reader must only ever
# see a COMPLETE entry from some writer generation — never a torn one.
# The corrupt-entry=miss tests above cover the read side; this is the
# write side under real cross-process contention.

_HAMMER_WRITER = r"""
import sys
import numpy as np
from hashcat_a5_table_generator_tpu.ops.packing import (
    PieceGroup, PieceSchema, save_piece_schema,
)

cache_dir, key, fill, rounds = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
)
group = PieceGroup(
    sel_cols=(0,), n_variants=4, n_words=2, off_cap=16, has_term=True,
    off_floor=0, len_fixed=None,
)
schema = PieceSchema(
    kind="match", groups=(group,), closed=False, max_out=16, n_cols=1,
    gw=np.full((64, 1, 4, 2), fill, dtype=np.uint32),  # ~128 KiB
    gl=np.full((64, 1, 4), fill, dtype=np.uint8),
    gw16=None, sel_bit=None, sel_slot=None,
)
for _ in range(rounds):
    save_piece_schema(cache_dir, key, schema)
print("WROTE")
"""


def test_two_process_schema_cache_write_hammer(tmp_path):
    """Two writer processes hammer the SAME cache key while this
    process reads it in a loop: with the durable atomic replace, the
    entry — once it exists — is always a complete generation from ONE
    writer (its arrays uniformly that writer's fill value), and never
    degrades back to a miss (a miss after a hit would mean a reader
    saw a torn or half-renamed file)."""
    import subprocess
    import sys as _sys

    import numpy as np

    from hashcat_a5_table_generator_tpu.ops.packing import (
        load_piece_schema,
    )

    cache = str(tmp_path / "cache")
    key = "hammered"
    writers = [
        subprocess.Popen(
            [_sys.executable, "-c", _HAMMER_WRITER, cache, key,
             str(fill), "60"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for fill in (1, 2)
    ]
    try:
        seen_fills = set()
        seen_hit = False
        deadline = __import__("time").monotonic() + 60
        while any(w.poll() is None for w in writers):
            assert __import__("time").monotonic() < deadline
            hit, schema = load_piece_schema(cache, key)
            if not hit:
                assert not seen_hit, (
                    "entry vanished/teared after a successful read"
                )
                continue
            seen_hit = True
            assert schema is not None
            gw = np.asarray(schema.gw)
            fills = set(np.unique(gw).tolist())
            assert len(fills) == 1, f"torn entry: mixed fills {fills}"
            assert int(np.unique(np.asarray(schema.gl))[0]) in fills
            seen_fills |= fills
        for w in writers:
            out, err = w.communicate(timeout=30)
            assert w.returncode == 0, err.decode()[-500:]
            assert b"WROTE" in out
        # Final state: a complete entry from one of the two writers.
        hit, schema = load_piece_schema(cache, key)
        assert hit and schema is not None
        assert seen_hit and seen_fills <= {1, 2}
        # No tmp litter survives the contention.
        import os as _os

        assert [
            n for n in _os.listdir(cache) if ".tmp." in n
        ] == []
    finally:
        for w in writers:
            if w.poll() is None:
                w.kill()
                w.wait()

"""Runtime compile-cache analyzer (tools/graftlint/runtime.py) around the
hot expand->hash->match path: the production sweep launches ONE compiled
program per (geometry, config), so any per-launch recompilation is a
cache-busting argument signature — on TPU a multi-second stall every
launch.  The static rules (GL006) catch the shapes of this bug; this is
the runtime gate that catches the event itself."""

import hashlib

import jax
import jax.numpy as jnp
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    digest_arrays,
    make_crack_step,
    plan_arrays,
    table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

LEET = {b"a": [b"4", b"@"], b"s": [b"5", b"$"], b"o": [b"0"], b"e": [b"3"]}
WORDS = [b"password", b"assassin", b"glasses"]
STRIDE = 128
NB = 4  # blocks per launch -> 512 lanes


def _fixed_stride_batches(plan, min_batches=2):
    """Cut the keyspace into >= min_batches same-shape launches (the
    production fixed-stride TPU geometry: padded to NB blocks)."""
    batches = []
    w = rank = 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=NB * STRIDE,
            max_blocks=NB, fixed_stride=STRIDE,
        )
        if batch.total == 0:
            break
        batches.append(pad_batch(batch, NB))
    assert len(batches) >= min_batches, "keyspace too small for the test"
    return batches


class TestHotPathCacheStability:
    def test_crack_step_compiles_once_across_launches(self, compile_watcher):
        """Launch-to-launch, only block VALUES change — the compiled
        program must be reused (zero new cache entries after warmup)."""
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        plan = build_plan(spec, ct, pack_words(WORDS))
        ds = build_digest_set(
            [hashlib.md5(b"decoy").digest()], spec.algo
        )
        step = make_crack_step(
            spec, num_lanes=NB * STRIDE, out_width=plan.out_width,
            block_stride=STRIDE,
        )
        p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
        batches = _fixed_stride_batches(plan)

        watcher = compile_watcher(step)
        # Warmup launch: exactly one trace+compile for the whole step.
        with watcher.expect(1, label="warmup"):
            int(step(p, t, block_arrays(batches[0]), d)["n_emitted"])
        # Every further launch: same signature, zero compiles.
        with watcher.expect(0, label="steady-state launches"):
            for batch in batches[1:]:
                int(step(p, t, block_arrays(batch), d)["n_emitted"])

    def test_digest_set_swap_does_not_recompile(self, compile_watcher):
        """Re-targeting (new digest values, same digest-set geometry)
        must not recompile — the sweep reuses the step across target
        reloads."""
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        plan = build_plan(spec, ct, pack_words(WORDS))
        step = make_crack_step(
            spec, num_lanes=NB * STRIDE, out_width=plan.out_width,
            block_stride=STRIDE,
        )
        p, t = plan_arrays(plan), table_arrays(ct)
        blocks = block_arrays(_fixed_stride_batches(plan)[0])

        d1 = digest_arrays(build_digest_set(
            [hashlib.md5(b"one").digest()], spec.algo))
        d2 = digest_arrays(build_digest_set(
            [hashlib.md5(b"two").digest()], spec.algo))
        watcher = compile_watcher(step)
        int(step(p, t, blocks, d1)["n_emitted"])  # warmup
        with watcher.expect(0, label="digest swap"):
            int(step(p, t, blocks, d2)["n_emitted"])


class TestWatcherSelfCheck:
    """The analyzer itself must detect misses, or the guards above are
    vacuous."""

    def test_detects_shape_bust(self, compile_watcher):
        f = jax.jit(lambda x: x * 2)
        watcher = compile_watcher(f)
        f(jnp.ones((4,), jnp.int32)).block_until_ready()
        with pytest.raises(AssertionError, match="cache-busting"):
            with watcher.expect(0):
                # New shape: a fresh signature-cache entry.
                f(jnp.ones((5,), jnp.int32)).block_until_ready()

    def test_counts_warmup_compile(self, compile_watcher):
        f = jax.jit(lambda x: x + 1)
        watcher = compile_watcher(f)
        with watcher.expect(1):
            f(jnp.ones((3,), jnp.int32)).block_until_ready()
        assert watcher.new_entries() == 1

    def test_cache_hit_is_silent(self, compile_watcher):
        f = jax.jit(lambda x: x - 1)
        watcher = compile_watcher(f)
        f(jnp.ones((2,), jnp.int32)).block_until_ready()
        with watcher.expect(0):
            f(jnp.ones((2,), jnp.int32) * 7).block_until_ready()

"""Table parser tests — the L2 parity contract (SURVEY.md §2.1, main.go:108-162)."""

import pytest

from hashcat_a5_table_generator_tpu.tables.parser import (
    HexDecodeError,
    TableLineError,
    decode_hex_notation,
    merge_substitution_tables,
    parse_substitution_table,
)


class TestHexNotation:
    def test_passthrough_plain_value(self):
        assert decode_hex_notation(b"abc") == b"abc"

    def test_decodes_basic(self):
        assert decode_hex_notation(b"$HEX[414243]") == b"ABC"

    def test_case_insensitive(self):
        assert decode_hex_notation(b"$HEX[aBcD]") == b"\xab\xcd"

    def test_spaces_stripped(self):
        # space-delimited hex is accepted (README.MD:172-176)
        assert decode_hex_notation(b"$HEX[41 42 43]") == b"ABC"

    def test_too_short_is_passthrough(self):
        # "$HEX[]" is 6 bytes < 7 => returned verbatim (main.go:149)
        assert decode_hex_notation(b"$HEX[]") == b"$HEX[]"

    def test_odd_length_raises(self):
        with pytest.raises(HexDecodeError):
            decode_hex_notation(b"$HEX[abc]")

    def test_nonhex_raises(self):
        with pytest.raises(HexDecodeError):
            decode_hex_notation(b"$HEX[zz]")

    def test_unwrapped_prefix_passthrough(self):
        assert decode_hex_notation(b"$HEX[41") == b"$HEX[41"


class TestParse:
    def test_basic_lines(self):
        table = parse_substitution_table(b"a=b\nc=d\n")
        assert table == {b"a": [b"b"], b"c": [b"d"]}

    def test_comments_and_blanks_skipped(self):
        table = parse_substitution_table(b"# comment\n\n  \na=b\n")
        assert table == {b"a": [b"b"]}

    def test_no_equals_silently_skipped(self):
        # main.go:124-126
        table = parse_substitution_table(b"noequals\na=b\n")
        assert table == {b"a": [b"b"]}

    def test_split_at_first_equals_value_may_contain_equals(self):
        table = parse_substitution_table(b"a=b=c\n")
        assert table == {b"a": [b"b=c"]}

    def test_empty_key_line(self):
        # "=x" and "==x" both yield an empty-key entry (SURVEY.md §2.1)
        table = parse_substitution_table(b"=x\n==y\n")
        assert table == {b"": [b"x", b"=y"]}

    def test_repeated_key_appends_in_order(self):
        table = parse_substitution_table(b"a=1\na=2\n")
        assert table == {b"a": [b"1", b"2"]}

    def test_duplicate_lines_kept(self):
        # Q7: no dedupe — duplicate lines => duplicate candidates downstream
        table = parse_substitution_table(b"a=X\na=X\n")
        assert table == {b"a": [b"X", b"X"]}

    def test_hex_on_both_sides(self):
        table = parse_substitution_table(b"$HEX[3d]=$HEX[2020]\n")
        assert table == {b"=": [b"  "]}

    def test_bad_hex_skips_line_and_reports(self):
        messages = []
        table = parse_substitution_table(
            b"$HEX[zz]=x\na=b\nc=$HEX[123]\n", on_skip=messages.append
        )
        assert table == {b"a": [b"b"]}
        assert len(messages) == 2
        assert "key" in messages[0] and "value" in messages[1]

    def test_crlf_lines(self):
        # qwerty-azerty.table is CRLF-terminated
        table = parse_substitution_table(b"a=b\r\nc=d\r\n")
        assert table == {b"a": [b"b"], b"c": [b"d"]}

    def test_whitespace_trimmed(self):
        table = parse_substitution_table(b"  a=b\t\n")
        assert table == {b"a": [b"b"]}

    def test_multichar_and_multibyte_keys(self):
        # byte-string keys: "ss=ß" (german.table:7), UTF-8 both sides
        table = parse_substitution_table("ss=ß\nε=ר\n".encode())
        assert table == {b"ss": ["ß".encode()], "ε".encode(): ["ר".encode()]}

    def test_oversized_line_raises(self):
        # Go's bufio.Scanner would abort the file here (Q8 analog for tables)
        with pytest.raises(TableLineError):
            parse_substitution_table(b"a=" + b"x" * 70000 + b"\n")

    def test_value_with_dollar_not_hex(self):
        table = parse_substitution_table(b"*=$\n")
        assert table == {b"*": [b"$"]}


class TestMerge:
    def test_later_tables_append_alternatives(self):
        # main.go:40-50: values append per key across files, in file order
        merged = merge_substitution_tables(
            [{b"a": [b"1"]}, {b"a": [b"2"], b"b": [b"3"]}]
        )
        assert merged == {b"a": [b"1", b"2"], b"b": [b"3"]}

    def test_same_mapping_twice_duplicates(self):
        merged = merge_substitution_tables([{b"a": [b"X"]}, {b"a": [b"X"]}])
        assert merged == {b"a": [b"X", b"X"]}


class TestReferenceArtifacts:
    def test_parse_all_builtin_tables(self, reference_tables):
        for path in sorted(reference_tables.glob("*.table")):
            table = parse_substitution_table(path.read_bytes(), source=str(path))
            assert table, path

    def test_qwerty_cyrillic_multi_option_keys(self, reference_tables):
        table = parse_substitution_table(
            (reference_tables / "qwerty-cyrillic.table").read_bytes()
        )
        assert table[b";"] == ["ж".encode(), "Ж".encode()]
        assert table[b"q"] == ["й".encode()]

    def test_german_multichar_key(self, reference_tables):
        table = parse_substitution_table(
            (reference_tables / "german.table").read_bytes()
        )
        assert table[b"ss"] == ["ß".encode()]
        assert table[b"Z"] == ["ß".encode()]

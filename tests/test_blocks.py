"""Block scheduler: the vectorized fixed-stride cutter must be
indistinguishable from the scalar loop (same blocks, same cursors), across
plan kinds, strides, and resume points — it feeds every device launch, so
any divergence silently corrupts sweep output."""

import numpy as np
import pytest

import hashcat_a5_table_generator_tpu.ops.blocks as blocks_mod
from hashcat_a5_table_generator_tpu.models.attack import AttackSpec, build_plan
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

LEET = {
    b"a": [b"4", b"@"],
    b"e": [b"3"],
    b"l": [b"1", b"|"],
    b"o": [b"0"],
    b"s": [b"5", b"$"],
    b"ss": [b"\xc3\x9f"],
}
WORDS = [
    b"glass", b"password", b"x", b"", b"hello", b"assassin", b"qqq",
    b"lessons", b"aeolus", b"misses",
]


def _plans():
    ct = compile_table(LEET)
    packed = pack_words(WORDS)
    out = []
    for mode in ("default", "reverse", "suball"):
        out.append(build_plan(AttackSpec(mode=mode, algo="md5"), ct, packed))
    # Windowed plan: tight window switches to scalar-rank cursors.
    out.append(
        build_plan(
            AttackSpec(mode="default", algo="md5", min_substitute=1,
                       max_substitute=1),
            ct, packed,
        )
    )
    return out


def _sweep_all(plan, stride, max_blocks, *, force_scalar, monkeypatch):
    """Cut the plan's whole space; returns the list of batches + cursors."""
    if force_scalar:
        monkeypatch.setattr(
            blocks_mod, "_make_blocks_stride_fast",
            lambda *a, **k: None,
        )
    out = []
    w = rank = 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank,
            max_variants=stride * max_blocks, max_blocks=max_blocks,
            fixed_stride=stride,
        )
        out.append((batch, w, rank))
        if batch.total == 0:
            break
        assert len(out) < 10_000, "cutter failed to advance"
    return out


@pytest.mark.parametrize("stride", [4, 16, 128])
@pytest.mark.parametrize("max_blocks", [3, 64])
def test_fast_cutter_matches_scalar(stride, max_blocks, monkeypatch):
    for plan in _plans():
        with monkeypatch.context() as m:
            slow = _sweep_all(plan, stride, max_blocks,
                              force_scalar=True, monkeypatch=m)
        fast = _sweep_all(plan, stride, max_blocks,
                          force_scalar=False, monkeypatch=monkeypatch)
        assert len(slow) == len(fast)
        for (bs, ws, rs), (bf, wf, rf) in zip(slow, fast):
            np.testing.assert_array_equal(bs.word, bf.word)
            np.testing.assert_array_equal(bs.base_digits, bf.base_digits)
            np.testing.assert_array_equal(bs.count, bf.count)
            np.testing.assert_array_equal(bs.offset, bf.offset)
            # Cursors may differ in normalization (the scalar loop can
            # return rank == total where the fast path returns the next
            # word at rank 0); they must still resume identically, which
            # the lockstep walk above already proves — but both must agree
            # once normalized.
            def norm(w, rank):
                while w < plan.batch and (
                    plan.fallback[w] or rank >= plan.n_variants[w]
                ):
                    w, rank = w + 1, 0
                return w, rank

            assert norm(ws, rs) == norm(wf, rf)


def test_misaligned_resume_rank_stays_correct(monkeypatch):
    """A checkpoint taken at one geometry can resume at another, so
    start_rank need not be stride-aligned; the scalar path covers it and
    the stream stays loss-free from that rank onward."""
    plan = _plans()[0]
    # Find a word with enough variants to split.
    w0 = max(range(plan.batch), key=lambda i: plan.n_variants[i])
    total = plan.n_variants[w0]
    assert total >= 8
    start_rank = 3  # not a multiple of any stride used below
    batch, w, rank = make_blocks(
        plan, start_word=w0, start_rank=start_rank,
        max_variants=64, max_blocks=64, fixed_stride=4,
    )
    covered = []
    for i in range(len(batch.count)):
        if int(batch.word[i]) != w0:
            continue
        radices = [int(r) for r in plan.pat_radix[w0]]
        base = 0
        scale = 1
        for s, r in enumerate(radices):
            base += int(batch.base_digits[i, s]) * scale
            scale *= r
        covered.extend(range(base, base + int(batch.count[i])))
    want = list(range(start_rank, min(total, start_rank + len(covered))))
    assert covered[: len(want)] == want


def test_huge_word_routes_to_scalar_path():
    class HugePlan:
        batch = 1
        num_slots = 64
        n_variants = (1 << 64,)
        fallback = np.zeros(1, dtype=bool)
        pat_radix = np.full((1, 64), 2, dtype=np.int32)
        windowed = False

    plan = HugePlan()
    batch, w, rank = make_blocks(
        plan, start_word=0, start_rank=0, max_variants=256,
        max_blocks=4, fixed_stride=64,
    )
    assert len(batch.count) == 4
    assert int(batch.count.sum()) == 256
    assert (batch.word == 0).all()
    assert rank == 256


def test_zero_block_budget_preserves_cursor():
    """Advisor r4: a zero block budget (max_variants < stride, or
    max_blocks == 0) with unfinished words must return the incoming
    cursor, not 'sweep complete' — on both cutter paths."""
    for plan in _plans():
        for kwargs in (
            dict(max_variants=0, max_blocks=4),       # budget < stride
            dict(max_variants=256, max_blocks=0),     # no blocks allowed
        ):
            batch, w, rank = make_blocks(
                plan, start_word=0, start_rank=0, fixed_stride=4, **kwargs
            )
            assert batch.total == 0
            assert (w, rank) != (plan.batch, 0)
            # The cursor may lazily normalize past fallback/empty words but
            # must still point at unswept keyspace.
            assert w < plan.batch
            assert rank < plan.n_variants[w]

        # Mid-sweep: advance one window, then hit a zero budget.
        _, w1, r1 = make_blocks(
            plan, start_word=0, start_rank=0, max_variants=8,
            max_blocks=2, fixed_stride=4,
        )
        if w1 >= plan.batch:
            continue
        batch, w2, r2 = make_blocks(
            plan, start_word=w1, start_rank=r1, max_variants=0,
            max_blocks=2, fixed_stride=4,
        )
        assert batch.total == 0 and (w2, r2) == (w1, r1)


def test_huge_word_mid_list_fast_scalar_agree(monkeypatch):
    """A huge word BETWEEN normal words: windows that touch it must fall
    back to the scalar cutter and stay block-for-block identical to a
    forced-scalar sweep (huge words get width 1 in the cumulative index)."""

    class MixedPlan:
        batch = 3
        num_slots = 64
        n_variants = (96, 1 << 64, 40)
        fallback = np.zeros(3, dtype=bool)
        pat_radix = np.full((3, 64), 2, dtype=np.int32)
        windowed = False

    def cut(force_scalar, n_calls=4):
        if force_scalar:
            monkeypatch.setattr(
                blocks_mod, "_make_blocks_stride_fast",
                lambda *a, **k: None,
            )
        out, w, rank = [], 0, 0
        for _ in range(n_calls):
            batch, w, rank = make_blocks(
                MixedPlan(), start_word=w, start_rank=rank,
                max_variants=128, max_blocks=4, fixed_stride=32,
            )
            out.append((
                batch.word.tolist(), batch.base_digits.tolist(),
                batch.count.tolist(), batch.offset.tolist(), w, rank,
            ))
        monkeypatch.undo()
        return out

    assert cut(False) == cut(True)

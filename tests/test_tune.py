"""Autotuned launch geometry (PERF.md §29): profile round-trip and
precedence, corrupt-profile fallback, the matrix driver's per-arm
parity + partial-matrix resume, and the Sweep's launch-time resolution
seam (explicit flag > loaded profile > built-in defaults) with its
``geometry_source`` provenance stamp.

The suite-wide ``A5GEN_TUNE_PROFILE=off`` (conftest) keeps every other
test hermetic; tests here point the env var at their own tmp dir."""

import hashlib
import json

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig
from hashcat_a5_table_generator_tpu.runtime.tune import (
    TUNE_SCHEMA_VERSION,
    TuneProfileCorrupt,
    builtin_geometry,
    default_matrix,
    device_slug,
    load_profile,
    profile_path,
    read_profile,
    resolve_config,
    run_autotune,
    tune_wordlist,
    write_profile,
)

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a"]

#: A tiny 2-arm matrix: one warm + one timed sweep per arm at
#: ``seconds=0.0`` keeps the whole matrix inside a couple of seconds on
#: the CPU backend (tier-1 budget).
TINY_MATRIX = [
    {"name": "lanes256-stride64", "lanes": 256, "num_blocks": 4,
     "stride": 64, "superstep": None, "pair": "auto", "emit": None},
    {"name": "lanes512-stride64", "lanes": 512, "num_blocks": 8,
     "stride": 64, "superstep": None, "pair": "auto", "emit": None},
]


def tiny_autotune(tmp_path, **kw):
    kw.setdefault("words", 64)
    kw.setdefault("seconds", 0.0)
    kw.setdefault("matrix", [dict(a) for a in TINY_MATRIX])
    kw.setdefault("directory", str(tmp_path / "profiles"))
    return run_autotune(**kw)


class TestProfileRoundTrip:
    def test_write_then_read_preserves_geometry(self, tmp_path):
        d = str(tmp_path)
        geometry = {"lanes": 1 << 17, "num_blocks": 256, "superstep": 8,
                    "pair": None, "packed_blocks": None}
        path = write_profile("TPU v5 lite", geometry,
                            bench={"hashes_per_s": 1.0}, directory=d)
        assert path == profile_path("TPU v5 lite", d)
        doc = read_profile(path)
        assert doc["version"] == TUNE_SCHEMA_VERSION
        assert doc["device_kind"] == "TPU v5 lite"
        for k, v in geometry.items():
            assert doc["geometry"][k] == v
        assert load_profile("TPU v5 lite", d) == doc

    def test_device_slug_is_filesystem_safe(self):
        assert device_slug("TPU v4") == "tpu-v4"
        assert device_slug("cpu") == "cpu"
        assert "/" not in device_slug("weird/kind (x)")

    def test_atomic_write_leaves_no_temp_droppings(self, tmp_path):
        write_profile("cpu", {"lanes": 1024}, directory=str(tmp_path))
        names = {p.name for p in tmp_path.iterdir()}
        assert names == {"cpu.json"}


class TestPrecedence:
    """Per-knob: explicit (non-None) > profile > built-in defaults."""

    def test_explicit_lanes_never_consults_profile(self, tmp_path):
        d = str(tmp_path)
        write_profile("cpu", {"lanes": 2048, "num_blocks": 4}, directory=d)
        cfg = SweepConfig(lanes=1 << 12, num_blocks=8)
        resolved, source = resolve_config(cfg, "cpu", directory=d)
        assert source == "explicit"
        assert resolved is cfg

    def test_profile_fills_unset_knobs(self, tmp_path):
        d = str(tmp_path)
        write_profile("cpu", {"lanes": 2048, "num_blocks": 4,
                              "superstep": 4}, directory=d)
        resolved, source = resolve_config(
            SweepConfig(lanes=None, num_blocks=None), "cpu", directory=d
        )
        assert source == "profile"
        assert (resolved.lanes, resolved.num_blocks, resolved.superstep) \
            == (2048, 4, 4)

    def test_explicit_knob_composes_with_profile(self, tmp_path):
        d = str(tmp_path)
        write_profile("cpu", {"lanes": 2048, "num_blocks": 4}, directory=d)
        resolved, source = resolve_config(
            SweepConfig(lanes=None, num_blocks=16), "cpu", directory=d
        )
        assert source == "profile"
        assert resolved.lanes == 2048
        assert resolved.num_blocks == 16  # explicit per-knob value wins

    def test_no_profile_falls_back_to_builtins(self, tmp_path):
        resolved, source = resolve_config(
            SweepConfig(lanes=None, num_blocks=None), "cpu",
            directory=str(tmp_path / "empty"),
        )
        assert source == "default"
        builtin = builtin_geometry("cpu")
        assert resolved.lanes == builtin["lanes"]
        assert resolved.num_blocks == builtin["num_blocks"]

    def test_builtin_geometry_per_backend_class(self):
        assert builtin_geometry("cpu")["lanes"] == 1 << 17
        assert builtin_geometry("TPU v4")["lanes"] == 1 << 22
        assert builtin_geometry("TPU v4")["num_blocks"] is None


class TestCorruptProfiles:
    def _resolve(self, d):
        return resolve_config(SweepConfig(lanes=None), "cpu", directory=d)

    def test_torn_json_warns_once_and_falls_back(self, tmp_path, capsys):
        d = str(tmp_path)
        path = profile_path("cpu", d)
        with open(path, "w") as fh:
            fh.write('{"version": "1.0", "geometry": {"lan')  # torn
        with pytest.raises(TuneProfileCorrupt):
            read_profile(path)
        resolved, source = self._resolve(d)
        assert source == "default"
        assert resolved.lanes == builtin_geometry("cpu")["lanes"]
        # Loading again must not warn again (once per path+reason).
        self._resolve(d)
        err = capsys.readouterr().err
        assert err.count("ignoring tune profile") == 1

    def test_unknown_major_rejected(self, tmp_path):
        d = str(tmp_path)
        path = profile_path("cpu", d)
        with open(path, "w") as fh:
            json.dump({"version": "99.0",
                       "geometry": {"lanes": 64}}, fh)
        with pytest.raises(TuneProfileCorrupt, match="schema major"):
            read_profile(path)
        assert load_profile("cpu", d) is None

    def test_unknown_minor_is_additive(self, tmp_path):
        d = str(tmp_path)
        path = profile_path("cpu", d)
        with open(path, "w") as fh:
            json.dump({"version": "1.9", "future_field": True,
                       "geometry": {"lanes": 4096}}, fh)
        resolved, source = self._resolve(d)
        assert source == "profile"
        assert resolved.lanes == 4096

    def test_malformed_geometry_rejected(self, tmp_path):
        d = str(tmp_path)
        path = profile_path("cpu", d)
        with open(path, "w") as fh:
            json.dump({"version": "1.0",
                       "geometry": {"lanes": "huge"}}, fh)
        assert load_profile("cpu", d) is None
        assert self._resolve(d)[1] == "default"

    def test_disabled_via_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", "off")
        assert profile_path("cpu") is None
        assert load_profile("cpu") is None
        with pytest.raises(ValueError, match="disabled"):
            write_profile("cpu", {"lanes": 64})

    def test_env_overrides_directory(self, monkeypatch, tmp_path):
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", str(tmp_path))
        write_profile("cpu", {"lanes": 512, "num_blocks": 8})
        assert (tmp_path / "cpu.json").is_file()
        resolved, source = resolve_config(SweepConfig(lanes=None), "cpu")
        assert (source, resolved.lanes) == ("profile", 512)


class TestAutotuneMatrix:
    def test_smoke_matrix_measures_and_writes_profile(self, tmp_path):
        seen = []
        res = tiny_autotune(tmp_path, on_arm=seen.append)
        assert [r["arm"] for r in seen] == [a["name"] for a in TINY_MATRIX]
        # Per-arm parity: geometry never changes WHAT is emitted.
        assert len({r["emitted_per_sweep"] for r in seen}) == 1
        assert res["winner"] in {a["name"] for a in TINY_MATRIX}
        assert res["hashes_per_s"] == max(r["hashes_per_s"] for r in seen)
        # The profile round-trips through the resolution seam, and the
        # loaded-by-default geometry is the measured winner (>= every
        # other arm, so >= the built-in default arm when present).
        doc = read_profile(res["profile_path"])
        resolved, source = resolve_config(
            SweepConfig(lanes=None), res["device_kind"],
            directory=str(tmp_path / "profiles"),
        )
        assert source == "profile"
        assert resolved.lanes == res["geometry"]["lanes"] \
            == doc["geometry"]["lanes"]

    def test_parity_failure_raises(self, tmp_path):
        bad = [dict(TINY_MATRIX[0]), dict(TINY_MATRIX[1])]
        state = {"completed": {bad[0]["name"]: {
            "arm": bad[0]["name"], "geometry": dict(bad[0]),
            "emitted_per_sweep": 1, "hits_per_sweep": 0, "sweeps": 1,
            "seconds": 0.0, "hashes_per_s": 1.0,
        }}}
        sp = tmp_path / "state.json"
        sp.write_text(json.dumps(state))
        with pytest.raises(RuntimeError, match="parity"):
            tiny_autotune(tmp_path, matrix=bad, state_path=str(sp))

    def test_partial_matrix_resume_skips_completed_arms(self, tmp_path):
        sp = str(tmp_path / "state.json")
        first = tiny_autotune(tmp_path, matrix=[dict(TINY_MATRIX[0])],
                              state_path=sp, write=False)
        assert first["winner"] == TINY_MATRIX[0]["name"]
        seen = []
        second = tiny_autotune(tmp_path, state_path=sp, write=False,
                               on_arm=seen.append)
        resumed = {r["arm"]: r.get("resumed", False) for r in seen}
        assert resumed[TINY_MATRIX[0]["name"]] is True
        assert resumed[TINY_MATRIX[1]["name"]] is False
        assert len(second["arms"]) == 2
        # Third run: the state file now covers the full matrix.
        third = tiny_autotune(tmp_path, state_path=sp, write=False)
        assert all(r.get("resumed") for r in third["arms"])

    def test_corrupt_state_file_raises_typed(self, tmp_path):
        sp = tmp_path / "state.json"
        sp.write_text("{not json")
        with pytest.raises(TuneProfileCorrupt, match="tune state"):
            tiny_autotune(tmp_path, state_path=str(sp))

    def test_default_matrix_smoke_is_tiny_and_full_is_bounded(self):
        smoke = default_matrix(smoke=True)
        full = default_matrix()
        assert 1 < len(smoke) <= 4
        assert len(smoke) < len(full) <= 64
        names = [a["name"] for a in full]
        assert len(set(names)) == len(names)
        for arm in smoke + full:
            assert arm["lanes"] % arm["stride"] == 0
            assert arm["num_blocks"] == arm["lanes"] // arm["stride"]

    def test_tune_wordlist_is_deterministic(self):
        assert tune_wordlist(16) == tune_wordlist(16)
        assert len(tune_wordlist(16)) == 16


class TestSweepResolutionSeam:
    """The runtime surface: a Sweep constructed with ``lanes=None``
    resolves geometry at launch time and stamps the provenance into
    the result; explicit constructions never consult a profile."""

    def _crack(self, cfg):
        spec = AttackSpec(mode="default", algo="md5")
        cand = next(iter(iter_candidates(WORDS[0], LEET, 0, 15)))
        digests = [hashlib.md5(cand).digest()]
        return Sweep(spec, LEET, WORDS, digests, config=cfg).run_crack()

    def test_explicit_geometry_stamped_explicit(self):
        res = self._crack(SweepConfig(lanes=64, num_blocks=16))
        assert res.geometry_source == "explicit"
        assert res.geometry["lanes"] == 64
        assert res.geometry["num_blocks"] == 16
        assert res.geometry["device_kind"] == "cpu"

    def test_profile_geometry_loaded_by_default(self, monkeypatch,
                                                tmp_path):
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", str(tmp_path))
        write_profile("cpu", {"lanes": 128, "num_blocks": 4})
        explicit = self._crack(SweepConfig(lanes=64, num_blocks=16))
        res = self._crack(SweepConfig(lanes=None, num_blocks=None))
        assert res.geometry_source == "profile"
        assert res.geometry["lanes"] == 128
        assert res.geometry["num_blocks"] == 4
        # Geometry never changes WHAT is emitted.
        assert res.n_emitted == explicit.n_emitted
        assert [h.candidate for h in res.hits] \
            == [h.candidate for h in explicit.hits]

    def test_corrupt_profile_falls_back_to_defaults(self, monkeypatch,
                                                    tmp_path):
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", str(tmp_path))
        (tmp_path / "cpu.json").write_text("{torn")
        # Small words list: built-in cpu default lanes (2^17) is one
        # launch over this wordlist — cheap.
        res = self._crack(SweepConfig(lanes=None))
        assert res.geometry_source == "default"
        assert res.geometry["lanes"] == builtin_geometry("cpu")["lanes"]

    def test_progress_lines_carry_geometry(self, monkeypatch, tmp_path,
                                           capsys):
        import io

        from hashcat_a5_table_generator_tpu.runtime.progress import (
            ProgressReporter,
        )

        monkeypatch.setenv("A5GEN_TUNE_PROFILE", str(tmp_path))
        write_profile("cpu", {"lanes": 128, "num_blocks": 4})
        buf = io.StringIO()
        progress = ProgressReporter(len(WORDS), every_s=0.0, stream=buf)
        self._crack(SweepConfig(lanes=None, progress=progress))
        lines = [json.loads(line) for line in
                 buf.getvalue().strip().splitlines()]
        assert lines
        geom = lines[-1]["progress"]["geometry"]
        assert geom["source"] == "profile"
        assert geom["lanes"] == 128

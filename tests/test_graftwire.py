"""graftwire (PERF.md §25–§27): the wire-protocol contract audit.

Static half: every GW check must both FLAG its broken fixture and stay
quiet on the clean twin (``tests/lint_fixtures/wire/``), the shipped
serve/fleet tier must analyze clean (the lint.sh layer-6 gate as a
test, asserted NON-vacuous via the extraction counters), and the
committed ``PROTOCOL.json`` pin must match the live registry (with the
``--update-protocol`` bump rule unit-tested).

Dynamic half: the ``runtime/protocol.py`` constructors must be
emission-identical to the historical inline dicts — ``json.dumps`` key
order IS the wire bytes the fleet parity suites pin — and the
checkpoint wire doc must round-trip unknown minor-newer fields
(``state_from_doc -> state_to_doc``), the replicated-ledger handoff
guarantee ROADMAP item 4 depends on.

Everything here is fast-tier: AST analysis plus pure-dict assertions,
no engines, no JAX compilation.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from hashcat_a5_table_generator_tpu.runtime import protocol  # noqa: E402
from hashcat_a5_table_generator_tpu.runtime.checkpoint import (  # noqa: E402
    CheckpointState,
    CheckpointWireIncompatible,
    SweepCursor,
    state_from_doc,
    state_to_doc,
    validate_checkpoint_doc,
)
from tools.graftwire import (  # noqa: E402
    ALL_CHECKS,
    analyze_paths,
    analyze_sources,
)
from tools.graftwire.allowlist import ALLOWLIST  # noqa: E402
from tools.graftwire.cli import DEFAULT_PATHS  # noqa: E402
from tools.graftwire.registry import (  # noqa: E402
    PinChange,
    check_bump,
    load_repo_registry,
    registry_to_pin,
)

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "lint_fixtures" \
    / "wire"
CODES = sorted(ALL_CHECKS)
RUNTIME_PATHS = [str(REPO_ROOT / p) for p in DEFAULT_PATHS]
GW006_PIN = str(FIXTURE_DIR / "gw006_pin.json")


def _fixture_kwargs(code):
    """GW006 diffs against its OWN fixture pin, never the repo's."""
    if code == "GW006":
        return {"pin_path": GW006_PIN}
    return {}


# ---------------------------------------------------------------------------
# Fixture corpus
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("code", CODES)
def test_check_flags_its_hazard(code):
    path = FIXTURE_DIR / f"{code.lower()}_flag.py"
    findings, _model = analyze_paths(
        [str(path)], select=[code], **_fixture_kwargs(code)
    )
    assert findings, f"{code} did not flag its broken fixture"
    assert all(f.code == code for f in findings)


@pytest.mark.parametrize("code", CODES)
def test_check_passes_the_clean_twin(code):
    path = FIXTURE_DIR / f"{code.lower()}_ok.py"
    findings, _model = analyze_paths(
        [str(path)], select=[code], **_fixture_kwargs(code)
    )
    assert not findings, (
        f"{code} false-positived on its clean twin: "
        + "; ".join(f.render() for f in findings)
    )


@pytest.mark.parametrize("code", CODES)
def test_fixture_pair_exists(code):
    for kind in ("flag", "ok"):
        assert (FIXTURE_DIR / f"{code.lower()}_{kind}.py").is_file()


def test_gw003_open_doc_is_skipped():
    """A ``**``-spread doc carries fields the AST cannot enumerate —
    it must not false-positive GW003 (the router's forwarded events
    are exactly this shape)."""
    src = (
        'WIRE_OPS = {}\n'
        'WIRE_EVENTS = {"failed": {"required": ["id", "error"],\n'
        '               "optional": [], "emitters": ["engine"],\n'
        '               "route": "dispatch"}}\n'
        'def fwd(base):\n'
        '    return {"event": "failed", **base}\n'
    )
    findings, _ = analyze_sources(
        [(src, "virt/open.py")], select=["GW003"]
    )
    assert not findings


def test_gw005_value_strings_stay_legal():
    """GW005 bans envelope KEY literals only: a dispatch chain's op
    VALUE strings (what graftrace GT004 extracts) must not trip it."""
    src = (
        "def dispatch(op):\n"
        "    if op == 'submit':\n"
        "        return 1\n"
        "    if op in ('pause', 'resume'):\n"
        "        return 2\n"
        "    return 0\n"
    )
    findings, _ = analyze_sources(
        [(src, "virt/values.py")], select=["GW005"]
    )
    assert not findings


# ---------------------------------------------------------------------------
# The repo-clean gate (non-vacuous)
# ---------------------------------------------------------------------------


def test_repo_runtime_is_clean():
    """The gate scripts/lint.sh layer 6 enforces, as a test: the
    serve/fleet tier must analyze clean against the live registry and
    the committed PROTOCOL.json."""
    findings, model = analyze_paths(RUNTIME_PATHS)
    assert not findings, "\n".join(f.render() for f in findings)
    # Non-vacuity: the extraction actually saw the protocol surfaces.
    assert model.registry is not None
    assert model.registry.path.endswith("protocol.py")
    assert len(model.registry.ops) >= 9
    assert len(model.registry.events) >= 12
    assert model.n_docs >= 30, "emission extraction went blind"
    assert model.n_dispatches >= 20, "dispatch extraction went blind"
    assert model.n_reads >= 20, "handler-read extraction went blind"
    owners = {
        d.owner
        for fs in model.surfaces
        for d in fs.dispatches
    }
    assert "_JsonlSession._handle" in owners
    assert "_RouterSession._handle" in owners
    assert any(o.endswith("._on_job_event") for o in owners)
    assert model.pin is not None, "PROTOCOL.json not loaded"
    assert model.changes == []


def test_registry_extraction_matches_import():
    """The AST-extracted registry IS the imported module's (the
    pure-literal contract): drift between the two would mean graftwire
    audits a phantom protocol."""
    reg = load_repo_registry()
    assert reg.version == protocol.PROTOCOL_VERSION
    assert reg.ops == protocol.WIRE_OPS
    assert reg.events == protocol.WIRE_EVENTS
    assert reg.checkpoint == protocol.CHECKPOINT_WIRE


def test_protocol_pin_matches_live_registry():
    pin = json.loads((REPO_ROOT / "PROTOCOL.json").read_text())
    assert pin == registry_to_pin(load_repo_registry())


def test_allowlist_is_live_and_shrink_only():
    """Every grandfather entry must still match a real finding: once
    the pattern is fixed, the entry MUST be deleted (shrink-only)."""
    findings, _ = analyze_paths(RUNTIME_PATHS, use_allowlist=False)
    for (suffix, key), why in ALLOWLIST.items():
        assert why.strip(), f"allowlist entry {key} needs a reason"
        assert any(
            f.path.replace("\\", "/").endswith(suffix) and f.key == key
            for f in findings
        ), (
            f"allowlist entry ({suffix}, {key}) matches no finding — "
            "the pattern was fixed; delete the entry"
        )


# ---------------------------------------------------------------------------
# The bump rule (--update-protocol)
# ---------------------------------------------------------------------------


def _add(detail="op 'probe' added"):
    return PinChange("addition", "op", "probe", detail)


def _rm(detail="op 'probe' removed"):
    return PinChange("removal", "op", "probe", detail)


def _meta(detail="note changed"):
    return PinChange("metadata", "op", "submit", detail)


def test_bump_rule():
    # additions need a minor (or major) bump
    assert check_bump("1.0", "1.0", [_add()]) is not None
    assert check_bump("1.0", "1.1", [_add()]) is None
    assert check_bump("1.0", "2.0", [_add()]) is None
    # removals/renames need a MAJOR bump — a minor does not satisfy
    assert check_bump("1.0", "1.1", [_rm()]) is not None
    assert check_bump("1.0", "2.0", [_rm()]) is None
    assert check_bump("1.0", "2.0", [_rm(), _add()]) is None
    # metadata-only re-pins need no bump but cannot move backwards
    assert check_bump("1.1", "1.1", [_meta()]) is None
    assert check_bump("1.1", "1.0", [_meta()]) is not None
    # unparseable versions are refused loudly
    with pytest.raises(ValueError):
        check_bump("banana", "1.0", [])


# ---------------------------------------------------------------------------
# Constructor byte parity (key order IS the wire bytes)
# ---------------------------------------------------------------------------


def test_constructor_byte_parity():
    """Each constructor must serialize byte-identically to the
    historical inline dict it replaced — the fleet parity suites pin
    whole JSONL streams on exactly these shapes."""
    d = json.dumps
    assert d(protocol.ev_accepted("j1", "crack")) == \
        '{"id": "j1", "event": "accepted", "kind": "crack"}'
    assert d(protocol.ev_accepted("j1", "crack", resumed=True)) == \
        '{"id": "j1", "event": "accepted", "kind": "crack", ' \
        '"resumed": true}'
    # router ack: engine rides even when None (admission-queued)
    assert d(protocol.ev_accepted("j1", "crack", engine=None,
                                  queued=True)) == \
        '{"id": "j1", "event": "accepted", "kind": "crack", ' \
        '"engine": null, "queued": true}'
    assert d(protocol.ev_hit("j1", digest="ab", plain_hex="cd",
                             word_index=3, rank="9")) == \
        '{"id": "j1", "event": "hit", "digest": "ab", ' \
        '"plain_hex": "cd", "word_index": 3, "rank": "9"}'
    assert d(protocol.ev_done("j1", n_hits=1, n_emitted=2,
                              wall_s=0.5, resumed=False)) == \
        '{"id": "j1", "event": "done", "n_hits": 1, ' \
        '"n_emitted": 2, "wall_s": 0.5, "resumed": false}'
    assert d(protocol.ev_done("j1", n_hits=1, n_emitted=2, wall_s=0.5,
                              resumed=True, ttfc_s=0.1,
                              schema_cache={"hits": 1},
                              spans=[1])) == \
        '{"id": "j1", "event": "done", "n_hits": 1, ' \
        '"n_emitted": 2, "wall_s": 0.5, "resumed": true, ' \
        '"ttfc_s": 0.1, "schema_cache": {"hits": 1}, "spans": [1]}'
    assert d(protocol.ev_paused("j1", {"c": 1})) == \
        '{"id": "j1", "event": "paused", "checkpoint": {"c": 1}}'
    assert d(protocol.ev_cancelled("j1")) == \
        '{"id": "j1", "event": "cancelled"}'
    assert d(protocol.ev_failed("j1", "boom")) == \
        '{"id": "j1", "event": "failed", "error": "boom"}'
    assert d(protocol.ev_failed("j1", "overloaded", reason="queue",
                                retry_after_s=1.5,
                                checkpoint={"c": 1})) == \
        '{"id": "j1", "event": "failed", "error": "overloaded", ' \
        '"reason": "queue", "retry_after_s": 1.5, ' \
        '"checkpoint": {"c": 1}}'
    assert d(protocol.ev_migrating("j1", frm="a", to="b")) == \
        '{"id": "j1", "event": "migrating", "from": "a", "to": "b"}'
    assert d(protocol.ev_migrating("j1", frm="a", to="a",
                                   noop=True)) == \
        '{"id": "j1", "event": "migrating", "from": "a", ' \
        '"to": "a", "noop": true}'
    assert d(protocol.ev_draining("e0", 2)) == \
        '{"event": "draining", "engine": "e0", "jobs": 2}'
    assert d(protocol.ev_stats({"jobs": 3})) == \
        '{"event": "stats", "jobs": 3}'
    assert d(protocol.ev_stats({"jobs": 3}, fleet={"engines": 1})) == \
        '{"event": "stats", "jobs": 3, "fleet": {"engines": 1}}'
    assert d(protocol.ev_metrics({"m": 1}, "# HELP\n")) == \
        '{"event": "metrics", "metrics": {"m": 1}, ' \
        '"prometheus": "# HELP\\n"}'
    assert d(protocol.ev_error("boom")) == \
        '{"event": "error", "error": "boom"}'
    assert d(protocol.ev_error("boom", jid="j1")) == \
        '{"event": "error", "error": "boom", "id": "j1"}'
    assert d(protocol.ev_error_overloaded("queue full", 2.0,
                                          jid="j1")) == \
        '{"event": "error", "error": "overloaded", ' \
        '"reason": "queue full", "retry_after_s": 2.0, "id": "j1"}'
    assert d(protocol.ev_bye()) == '{"event": "bye"}'
    assert d(protocol.op_pause("j1")) == '{"op": "pause", "id": "j1"}'
    assert d(protocol.op_cancel("j1")) == \
        '{"op": "cancel", "id": "j1"}'
    assert d(protocol.op_stats()) == '{"op": "stats"}'
    assert d(protocol.op_metrics()) == '{"op": "metrics"}'
    assert d(protocol.op_shutdown()) == '{"op": "shutdown"}'
    # op_submit stamps in place, preserving the client's key order
    sdoc = {"id": "j1", "words": ["a"]}
    out = protocol.op_submit(sdoc)
    assert out is sdoc
    assert d(out) == '{"id": "j1", "words": ["a"], "op": "submit"}'


def test_validate_doc():
    protocol.validate_doc(protocol.ev_failed("j1", "boom"))
    protocol.validate_doc(protocol.op_pause("j1"))
    protocol.validate_doc({"words": ["a"]})  # default op: submit
    # stats is an open doc: arbitrary scrape fields are the schema
    protocol.validate_doc({"event": "stats", "whatever": 1})
    with pytest.raises(ValueError, match="undeclared event"):
        protocol.validate_doc({"event": "vanished"})
    with pytest.raises(ValueError, match="undeclared op"):
        protocol.validate_doc({"op": "frobnicate"})
    with pytest.raises(ValueError, match="missing required"):
        protocol.validate_doc({"event": "failed", "id": "j1"})
    with pytest.raises(ValueError, match="missing required"):
        protocol.validate_doc({"op": "pause"})


# ---------------------------------------------------------------------------
# Checkpoint wire doc: forward compatibility (satellite of item 4)
# ---------------------------------------------------------------------------


def _state():
    return CheckpointState(
        fingerprint="f" * 64,
        cursor=SweepCursor(word=3, rank=10**20),
        n_emitted=5,
        n_hits=1,
        hits=[(2, 7)],
        wall_s=1.5,
    )


def test_checkpoint_doc_round_trip_is_stable():
    doc = state_to_doc(_state())
    assert "extra" not in doc  # empty carry adds no wire bytes
    state2 = state_from_doc(doc)
    assert state2.extra == {}
    assert state_to_doc(state2) == doc


def test_minor_newer_checkpoint_fields_survive_round_trip():
    """The replicated-ledger handoff guarantee: a minor-newer doc's
    unknown fields ride ``state_from_doc -> state_to_doc`` verbatim —
    an older router hop must not strip what a newer engine wrote."""
    doc = state_to_doc(_state())
    doc["wire_version"] = "1.7"
    doc["lineage"] = {"engine": "e9", "hop": 2}
    doc["salt_policy"] = "v2"
    state = state_from_doc(doc)
    assert state.extra == {
        "lineage": {"engine": "e9", "hop": 2}, "salt_policy": "v2",
    }
    out = state_to_doc(state)
    assert out["lineage"] == {"engine": "e9", "hop": 2}
    assert out["salt_policy"] == "v2"
    # this build re-stamps ITS wire version (same major: still legal
    # for the next 1.x reader) and never loses known fields to the
    # carry
    assert out["wire_version"] == "1.0"
    assert out["fingerprint"] == "f" * 64
    validate_checkpoint_doc(out)
    # majors still reject: forward-compat is minor-only
    doc["wire_version"] = "2.0"
    with pytest.raises(CheckpointWireIncompatible):
        state_from_doc(doc)


def test_validate_checkpoint_doc_on_constructor_path():
    """The capture-time validator accepts exactly what the paused/
    failed constructors carry (the checkpoint is the quarantine
    token), and still rejects the malformed shapes."""
    ck = state_to_doc(_state())
    ev = protocol.ev_paused("j1", ck)
    assert validate_checkpoint_doc(ev["checkpoint"]) is ck
    ev = protocol.ev_failed("j1", "boom", checkpoint=ck)
    assert validate_checkpoint_doc(ev["checkpoint"]) is ck
    bad = dict(ck)
    del bad["cursor"]
    with pytest.raises(Exception, match="missing required"):
        validate_checkpoint_doc(bad)
    with pytest.raises(Exception, match="JSON object"):
        validate_checkpoint_doc("not-a-doc")


def test_checkpoint_wire_mirror_stays_synced():
    """protocol.CHECKPOINT_WIRE mirrors checkpoint.py's constants
    (also asserted at import time — this pins the message)."""
    from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
        _WIRE_REQUIRED,
        WIRE_VERSION,
    )

    assert protocol.CHECKPOINT_WIRE["version"] == WIRE_VERSION
    assert protocol.CHECKPOINT_WIRE["required"] == list(_WIRE_REQUIRED)


# ---------------------------------------------------------------------------
# Router resume ack regression (the sweep's real find)
# ---------------------------------------------------------------------------


def test_router_resume_ack_carries_queued_flag():
    """The graftwire sweep's asymmetry fix: a resume that lands in the
    admission queue must say so — the router's resume ack now carries
    the ``queued`` flag exactly like the submit ack (and stays
    byte-identical when the job dispatched immediately)."""
    ack_direct = protocol.ev_accepted("j1", "crack", queued=False,
                                      resumed=True)
    assert json.dumps(ack_direct) == \
        '{"id": "j1", "event": "accepted", "kind": "crack", ' \
        '"resumed": true}'
    ack_queued = protocol.ev_accepted("j1", "crack", queued=True,
                                      resumed=True)
    assert json.dumps(ack_queued) == \
        '{"id": "j1", "event": "accepted", "kind": "crack", ' \
        '"queued": true, "resumed": true}'
    # the live call site passes the router ack's queued bit through
    import inspect

    from hashcat_a5_table_generator_tpu.runtime import fleet

    src = inspect.getsource(fleet._RouterSession._handle)
    assert 'queued=bool(ack.get("queued")), resumed=True' in src


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_artifacts(tmp_path):
    """0 clean / 1 findings / 2 usage error through the real CLI, plus
    the --report/--metrics-json artifact shapes CI uploads."""
    report = tmp_path / "wire.md"
    metrics = tmp_path / "metrics.json"
    clean = subprocess.run(
        [sys.executable, "-m", "tools.graftwire",
         *DEFAULT_PATHS,
         "--report", str(report), "--metrics-json", str(metrics)],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    md = report.read_text()
    assert "wire-protocol contract" in md
    assert "| `submit` (default) |" in md
    assert "in sync" in md
    payload = json.loads(metrics.read_text())["graftwire"]
    assert payload["findings"] == 0
    assert payload["ops"] >= 9 and payload["events"] >= 12
    assert payload["emissions"] >= 30
    flag = subprocess.run(
        [sys.executable, "-m", "tools.graftwire", "--select", "GW005",
         str(FIXTURE_DIR / "gw005_flag.py")],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert flag.returncode == 1
    assert "GW005" in flag.stdout
    drift = subprocess.run(
        [sys.executable, "-m", "tools.graftwire", "--select", "GW006",
         "--protocol-json", GW006_PIN,
         str(FIXTURE_DIR / "gw006_flag.py")],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert drift.returncode == 1
    assert "GW006" in drift.stdout
    usage = subprocess.run(
        [sys.executable, "-m", "tools.graftwire", "--select", "GW999"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert usage.returncode == 2


def test_readme_wire_section_is_fresh(tmp_path):
    """The committed README section matches the live registry (the CI
    staleness gate as a test), and a doctored section actually fails —
    the check is not vacuous."""
    fresh = subprocess.run(
        [sys.executable, "-m", "tools.graftwire",
         "--select", "GW006", "--check-readme", "README.md"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert fresh.returncode == 0, fresh.stdout + fresh.stderr
    stale_md = tmp_path / "README.md"
    stale_md.write_text(
        (REPO_ROOT / "README.md").read_text().replace(
            "| `submit` (default) |", "| `submit-old` |"
        )
    )
    stale = subprocess.run(
        [sys.executable, "-m", "tools.graftwire",
         "--select", "GW006", "--check-readme", str(stale_md)],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert stale.returncode == 1
    assert "stale" in stale.stderr

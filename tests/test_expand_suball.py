"""CPU<->TPU parity for the substitute-all expansion kernel.

Every fast-path word's device-enumerated candidate multiset must equal the
oracle's (``process_word_substitute_all``); fallback flags must fire exactly
when the fast path would be inexact."""

from collections import Counter

import jax.numpy as jnp
import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import (
    process_word_substitute_all,
)
from hashcat_a5_table_generator_tpu.ops.expand_suball import (
    build_suball_plan,
    expand_suball,
    make_blocks,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import BUILTIN_LAYOUTS
from hashcat_a5_table_generator_tpu.tables.parser import parse_substitution_table


def run_device_suball(sub_map, words, min_sub, max_sub, lanes=4096):
    """Enumerate the whole substitute-all space on the device path; returns
    ({word_index: Counter(candidates)}, fallback word indices)."""
    ct = compile_table(sub_map)
    packed = pack_words(words)
    plan = build_suball_plan(ct, packed)
    # Cascade-closed plans carry their own value table and joint-index
    # fields — exactly what models.attack._expand wires in production.
    val_bytes = ct.val_bytes if plan.cval_bytes is None else plan.cval_bytes
    val_len = ct.val_len if plan.cval_len is None else plan.cval_len
    close_kw = {}
    if plan.close_next is not None:
        close_kw = dict(
            close_next=jnp.asarray(plan.close_next),
            close_mul=jnp.asarray(plan.close_mul),
        )
    results = {i: Counter() for i in range(len(words))}
    w, rank = 0, 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=lanes
        )
        if batch.total == 0:
            break
        cand, cand_len, word_row, emit = expand_suball(
            jnp.asarray(plan.tokens),
            jnp.asarray(plan.lengths),
            jnp.asarray(plan.pat_radix),
            jnp.asarray(plan.pat_val_start),
            jnp.asarray(plan.seg_orig_start),
            jnp.asarray(plan.seg_orig_len),
            jnp.asarray(plan.seg_pat),
            jnp.asarray(val_bytes),
            jnp.asarray(val_len),
            jnp.asarray(batch.word),
            jnp.asarray(batch.base_digits),
            jnp.asarray(batch.count),
            jnp.asarray(batch.offset),
            num_lanes=lanes,
            out_width=plan.out_width,
            min_substitute=min_sub,
            max_substitute=max_sub,
            **close_kw,
        )
        cand = np.asarray(cand)
        cand_len = np.asarray(cand_len)
        word_row = np.asarray(word_row)
        emit = np.asarray(emit)
        for i in np.nonzero(emit)[0]:
            results[int(word_row[i])][bytes(cand[i, : cand_len[i]])] += 1
    return results, set(np.nonzero(plan.fallback)[0])


def assert_parity(sub_map, words, min_sub=0, max_sub=15):
    got, fallbacks = run_device_suball(sub_map, words, min_sub, max_sub)
    for i, word in enumerate(words):
        if i in fallbacks:
            continue
        want = Counter(
            process_word_substitute_all(word, sub_map, min_sub, max_sub)
        )
        assert got[i] == want, (word, min_sub, max_sub)
    return fallbacks


def test_single_byte_table_parity():
    sub_map = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"]}
    fb = assert_parity(sub_map, [b"password", b"gas", b"", b"zzz", b"aosaos"])
    assert not fb


def test_min_max_windows():
    sub_map = {b"a": [b"4"], b"o": [b"0"], b"s": [b"$"], b"e": [b"3"]}
    words = [b"aoese", b"sea", b"x"]
    for mn, mx in [(0, 15), (1, 2), (2, 2), (3, 3), (0, 0), (2, 1), (4, 9)]:
        assert_parity(sub_map, words, mn, mx)


def test_multibyte_values_length_change():
    sub_map = {b"s": [b"\xc3\x9f", b""], b"e": [b"\xd0\xad"]}  # grow and shrink
    fb = assert_parity(sub_map, [b"sees", b"s", b"esse"])
    assert not fb


def test_multibyte_keys():
    sub_map = {b"ss": [b"\xc3\x9f"], b"a": [b"4"]}
    fb = assert_parity(sub_map, [b"passsword", b"ssass", b"ssss"])
    assert not fb


def test_overlapping_patterns_fall_back():
    # "ab" and "b" overlap in "ab": chosen-subset-dependent spans -> fallback.
    sub_map = {b"ab": [b"X"], b"b": [b"Y"]}
    got, fallbacks = run_device_suball(sub_map, [b"ab", b"aab", b"cd"], 0, 15)
    assert 0 in fallbacks and 1 in fallbacks and 2 not in fallbacks


def test_cascade_hazard_closes_on_device():
    # 'b' sorts after 'a' and is inserted by it: a containment-only hazard
    # when both are present. Cascade closure keeps such words on the
    # device path (closed joint value tables), byte-parity with the
    # oracle; assert_parity checks every NON-fallback word.
    sub_map = {b"a": [b"b"], b"b": [b"c"]}
    fallbacks = assert_parity(sub_map, [b"ab", b"a", b"b", b"aabb"])
    assert not fallbacks
    ct = compile_table(sub_map)
    plan = build_suball_plan(ct, pack_words([b"ab", b"a", b"b"]))
    assert plan.closed is not None and list(plan.closed) == [
        True, False, False,
    ]
    # Words containing only one side of the hazard stay on the CLEAN path.
    assert_parity(sub_map, [b"a", b"b", b"xa", b"bx"])


def test_cascade_hazard_env_escape_hatch(monkeypatch):
    # A5GEN_CASCADE_CLOSE=off restores the pre-closure routing: every
    # hazard word falls back to the oracle.
    monkeypatch.setenv("A5GEN_CASCADE_CLOSE", "off")
    sub_map = {b"a": [b"b"], b"b": [b"c"]}
    _, fallbacks = run_device_suball(sub_map, [b"ab", b"a", b"b"], 0, 15)
    assert fallbacks == {0}


def test_cascade_boundary_crossing_falls_back():
    # 'cb' matches across the boundary of the value 'c' inserted by 'a' and
    # the adjacent original 'b' — no containment, but the ReplaceAll cascade
    # diverges from span splicing, so the word must fall back.
    sub_map = {b"a": [b"c"], b"cb": [b"Z"]}
    _, fallbacks = run_device_suball(sub_map, [b"abcb", b"acb", b"xcb"], 0, 15)
    assert 0 in fallbacks and 1 in fallbacks
    assert 2 not in fallbacks  # only 'cb' present: no inserter, no hazard
    assert_parity(sub_map, [b"xcb", b"aa", b"a"])


def test_cascade_shrink_merge_falls_back():
    # An empty value for 'a' merges its neighbors; 'bc' then matches across
    # the splice point ('bacbc' -> 'bcbc' -> ReplaceAll hits both).
    sub_map = {b"a": [b""], b"bc": [b"Z"]}
    _, fallbacks = run_device_suball(sub_map, [b"bacbc"], 0, 15)
    assert fallbacks == {0}


def test_duplicate_options_multiplicity():
    # Q7: duplicate table options must yield duplicate candidates.
    sub_map = {b"a": [b"X", b"X"]}
    got, _ = run_device_suball(sub_map, [b"za"], 0, 15)
    assert got[0] == Counter({b"za": 1, b"zX": 2})


def test_empty_key_table_all_fallback():
    _, fallbacks = run_device_suball({b"": [b"-"], b"a": [b"4"]}, [b"ab"], 0, 15)
    assert fallbacks == {0}


@pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
def test_builtin_table_parity(name):
    sub_map = BUILTIN_LAYOUTS[name].to_substitution_map()
    words = [
        b"password",
        b"hello",
        b"",
        b"a",
        b"zzzyyy",
        "καλημέρα".encode("utf-8"),
        b"Pa,ss",
    ]
    fallbacks = assert_parity(sub_map, words, 0, 15)
    if name != "qwerty-azerty":
        assert not fallbacks


def test_block_splitting_matches_whole_run():
    # Tiny lane budget forces many blocks with nonzero base digits; the union
    # must equal a single big run.
    sub_map = {b"a": [b"1", b"2", b"3"], b"b": [b"x", b"y"], b"c": [b"q"]}
    words = [b"abcabc", b"cab"]
    small, _ = run_device_suball(sub_map, words, 0, 15, lanes=7)
    big, _ = run_device_suball(sub_map, words, 0, 15, lanes=4096)
    assert small == big


def test_hex_table_roundtrip_parity():
    data = b"a=$HEX[c3 9f]\n$HEX[62]=B\n"
    sub_map = parse_substitution_table(data)
    fb = assert_parity(sub_map, [b"abba"])
    assert not fb


#: Inactive-column value per plan field (padding past each plan's used
#: slots/segments).
_INACTIVE = {
    "pat_radix": 1,
    "pat_val_start": 0,
    "seg_orig_start": 0,
    "seg_orig_len": 0,
    "seg_pat": -1,
}


def assert_fast_plan_equiv(fast, slow):
    """Fast-vs-scalar plan equivalence with the documented contract: flags,
    totals, windowed state, and pattern-slot fields match everywhere
    (fallback rows are neutralized in both paths); segment fields and the
    derived width match on NON-fallback rows (fallback words never reach
    the device — the scalar path stores its partially-claimed spans there,
    the fast path the independent ones). Axis widths may differ when a
    fallback word holds a path's slot/segment maximum, so fields compare
    over the common prefix with the remainder pinned to inactive values."""
    np.testing.assert_array_equal(fast.fallback, slow.fallback)
    assert fast.n_variants == slow.n_variants
    assert fast.windowed == slow.windowed
    live = ~fast.fallback
    p = min(fast.num_slots, slow.num_slots)
    for f in ("pat_radix", "pat_val_start"):
        np.testing.assert_array_equal(
            getattr(fast, f)[:, :p], getattr(slow, f)[:, :p], err_msg=f
        )
        for plan in (fast, slow):
            assert (getattr(plan, f)[:, p:] == _INACTIVE[f]).all(), f
    if fast.windowed:
        np.testing.assert_array_equal(
            fast.win_v[:, : p + 1], slow.win_v[:, : p + 1]
        )
    g = min(fast.num_segments, slow.num_segments)
    for f in ("seg_orig_start", "seg_orig_len", "seg_pat"):
        np.testing.assert_array_equal(
            getattr(fast, f)[live, :g], getattr(slow, f)[live, :g],
            err_msg=f,
        )
        # Any extra columns in the wider plan are inactive on live rows.
        for plan in (fast, slow):
            assert (getattr(plan, f)[live, g:] == _INACTIVE[f]).all(), f
    if not fast.fallback.any():
        assert fast.out_width == slow.out_width
    else:
        # Scalar width also covers fallback words' dead spans; fast sizes
        # only what the device will see.
        assert fast.out_width <= slow.out_width
    # Cascade-closure fields: identical classification, joint tables and
    # extended value rows (the dedup insertion order is word-ascending in
    # both paths, so even row ORDER must agree).
    b = fast.batch
    fc = fast.closed if fast.closed is not None else np.zeros(b, bool)
    sc = slow.closed if slow.closed is not None else np.zeros(b, bool)
    np.testing.assert_array_equal(fc, sc, err_msg="closed")
    assert fast.close_opts == slow.close_opts
    if fc.any():
        s_ax = fast.close_next.shape[2]
        assert slow.close_next.shape[2] == s_ax
        np.testing.assert_array_equal(
            fast.close_next[:, :p], slow.close_next[:, :p],
            err_msg="close_next",
        )
        np.testing.assert_array_equal(
            fast.close_mul[:, :p], slow.close_mul[:, :p],
            err_msg="close_mul",
        )
        for plan in (fast, slow):
            assert (plan.close_next[:, p:] == -1).all()
        np.testing.assert_array_equal(fast.cval_bytes, slow.cval_bytes)
        np.testing.assert_array_equal(fast.cval_len, slow.cval_len)


class TestFastPlanPath:
    """The vectorized plan builder must agree with the scalar reference
    path under the contract pinned by assert_fast_plan_equiv — it replaces
    the scalar silently for every no-empty-key table, so any divergence is
    invisible stream corruption."""

    TABLES = [
        {b"a": [b"1", b"2"], b"b": [b"x"], b"c": []},  # multi-option + empty
        {bytes([c]): [bytes([c - 32])] for c in b"abcdefghij"},  # toggle-ish
        {b"s": [b"\xc3\x9f", b"$"], b"e": [b"3"]},  # 2-byte values
        {b"ss": [b"\xc3\x9f"], b"a": [b"4"], b"b": [b"8"]},  # multi-char key
        {b"ab": [b"X"], b"bc": [b"Y"], b"c": [b"Z"]},  # overlap -> fallback
        {b"a": [b"b"], b"b": [b"c"]},  # cascade hazard pair (closable)
        {b"a": [b"bb"], b"b": [b"c", b"q"]},  # closable, multi-option succ
        {b"a": [b"c"], b"cb": [b"Z"]},  # crossing hazard -> pathological
    ]
    WORDS = [b"", b"a", b"abc", b"aabbcc", b"zzz", b"cabbage",
             b"mississippi", b"abcabcabc", b"q" * 20, b"sesames",
             b"strasse", b"bcbcab"]

    @pytest.mark.parametrize("first_option_only", [False, True])
    @pytest.mark.parametrize("window", [(None, None), (1, 2)])
    @pytest.mark.parametrize("ti", range(len(TABLES)))
    def test_fast_equals_scalar(self, ti, first_option_only, window,
                                monkeypatch):
        import hashcat_a5_table_generator_tpu.ops.expand_suball as es

        ct = compile_table(self.TABLES[ti])
        packed = pack_words(self.WORDS)
        mn, mx = window
        kw = dict(first_option_only=first_option_only,
                  min_substitute=mn, max_substitute=mx)
        fast = build_suball_plan(ct, packed, **kw)
        with monkeypatch.context() as m:
            m.setattr(es, "_build_suball_plan_fast", lambda *a, **k: None)
            slow = build_suball_plan(ct, packed, **kw)
        assert_fast_plan_equiv(fast, slow)

    def test_fallback_words_flagged(self):
        # The overlap table must actually route words to the oracle, so
        # the relaxed-contract branch of the equivalence is exercised.
        ct = compile_table(self.TABLES[4])
        plan = build_suball_plan(ct, pack_words(self.WORDS))
        assert plan.fallback.any() and not plan.fallback.all()

    def test_empty_key_table_keeps_scalar_path(self):
        ct = compile_table({b"": [b"x"], b"a": [b"4"]})
        assert ct.has_empty_key
        from hashcat_a5_table_generator_tpu.ops.expand_suball import (
            _build_suball_plan_fast,
        )

        assert _build_suball_plan_fast(
            ct, pack_words([b"strasse"]), first_option_only=False,
            out_width=None, min_substitute=None, max_substitute=None,
        ) is None

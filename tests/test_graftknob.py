"""graftknob (PERF.md §30): the configuration-knob contract audit.

Every GK check must both FLAG its broken fixture and stay quiet on the
clean twin (``tests/lint_fixtures/knobs/``), the shipped package must
analyze clean (the lint.sh layer-7 gate as a test, asserted NON-vacuous
via the extraction floors), the AST-extracted registry must equal the
imported ``runtime/knobs.py`` module's (the pure-literal contract), and
the committed ``KNOBS.json`` pin must match the live registry (with the
``--update-knobs`` bump rule unit-tested).

GK001–GK005 fixtures come in (surface file, registry companion) pairs:
a file that declares ``KNOBS`` is a registry SOURCE and is skipped for
surface extraction, so the miniature registry rides in its own
``gk00N_knobs.py`` alongside the flag/ok twin.  GK006 is registry-vs-
pin drift, so its fixtures ARE registries, diffed against the fixture's
own ``gk006_pin.json`` — never the repo's.

Everything here is fast-tier: AST analysis plus a few sub-second CLI
subprocesses, no engines, no JAX compilation.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from hashcat_a5_table_generator_tpu.runtime import knobs  # noqa: E402
from tools.graftknob import (  # noqa: E402
    ALL_CHECKS,
    REPO_FLOORS,
    analyze_paths,
    repo_floor_errors,
)
from tools.graftknob.allowlist import ALLOWLIST  # noqa: E402
from tools.graftknob.cli import DEFAULT_PATHS  # noqa: E402
from tools.graftknob.registry import (  # noqa: E402
    PinChange,
    check_bump,
    load_repo_registry,
    registry_to_pin,
)

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "lint_fixtures" \
    / "knobs"
CODES = sorted(ALL_CHECKS)
RUNTIME_PATHS = [str(REPO_ROOT / p) for p in DEFAULT_PATHS]
GK006_PIN = str(FIXTURE_DIR / "gk006_pin.json")


def _fixture_paths(code, kind):
    """GK001–GK005 analyze (surface, registry-companion) pairs; GK006's
    fixtures ARE registries, diffed against the fixture pin."""
    main = FIXTURE_DIR / f"{code.lower()}_{kind}.py"
    if code == "GK006":
        return [str(main)]
    return [str(main), str(FIXTURE_DIR / f"{code.lower()}_knobs.py")]


def _fixture_kwargs(code):
    if code == "GK006":
        return {"pin_path": GK006_PIN}
    return {}


# ---------------------------------------------------------------------------
# Fixture corpus
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("code", CODES)
def test_check_flags_its_hazard(code):
    findings, _model = analyze_paths(
        _fixture_paths(code, "flag"), select=[code],
        **_fixture_kwargs(code)
    )
    assert findings, f"{code} did not flag its broken fixture"
    assert all(f.code == code for f in findings)


@pytest.mark.parametrize("code", CODES)
def test_check_passes_the_clean_twin(code):
    findings, _model = analyze_paths(
        _fixture_paths(code, "ok"), select=[code],
        **_fixture_kwargs(code)
    )
    assert not findings, (
        f"{code} false-positived on its clean twin: "
        + "; ".join(f.render() for f in findings)
    )


@pytest.mark.parametrize("code", CODES)
def test_fixture_pair_exists(code):
    for kind in ("flag", "ok"):
        assert (FIXTURE_DIR / f"{code.lower()}_{kind}.py").is_file()
    if code != "GK006":
        assert (FIXTURE_DIR / f"{code.lower()}_knobs.py").is_file()


def test_gk001_both_directions():
    """The GK001 fixture is bidirectional by construction: the flag
    twin reads an undeclared env var AND leaves a declared knob dead —
    both findings must surface (one check, two failure modes)."""
    findings, _ = analyze_paths(
        _fixture_paths("GK001", "flag"), select=["GK001"]
    )
    keys = {f.key for f in findings}
    assert "env:A5GEN_GAMMA" in keys, "undeclared-read arm went blind"
    assert any(k.startswith("dead:") for k in keys), \
        "dead-declaration arm went blind"


def test_gk005_flags_both_surfaces():
    """Default drift is checked per surface: the flag twin drifts the
    dataclass AND the argparse default, and each gets its own keyed
    finding (fixing one must not mask the other)."""
    findings, _ = analyze_paths(
        _fixture_paths("GK005", "flag"), select=["GK005"]
    )
    keys = {f.key for f in findings}
    assert "default:config:lanes" in keys
    assert "default:cli:lanes" in keys


# ---------------------------------------------------------------------------
# The repo-clean gate (non-vacuous)
# ---------------------------------------------------------------------------


def test_repo_tree_is_clean():
    """The gate scripts/lint.sh layer 7 enforces, as a test: the
    package + bench.py must analyze clean against the live registry
    and the committed KNOBS.json."""
    findings, model = analyze_paths(RUNTIME_PATHS)
    assert not findings, "\n".join(f.render() for f in findings)
    # Non-vacuity: the extraction actually saw the knob surfaces and
    # every role's key site.
    assert model.registry is not None
    assert model.registry.path.endswith("knobs.py")
    assert repo_floor_errors(model) == []
    assert len(model.registry.knobs) >= REPO_FLOORS["knobs"]
    assert model.n_env_reads >= REPO_FLOORS["env_reads"]
    assert model.n_cli_flags >= REPO_FLOORS["cli_flags"]
    assert model.n_config_fields >= REPO_FLOORS["config_fields"]
    assert model.n_trace_sites >= REPO_FLOORS["trace_sites"]
    assert model.n_fuse_key_sites >= 1, "pack_candidate key went blind"
    assert model.n_fuse_guards >= REPO_FLOORS["fuse_guards"]
    assert model.n_affinity_sites >= 1, "affinity_token went blind"
    assert model.n_fingerprint_sites >= 1, \
        "sweep_fingerprint went blind"
    assert model.n_serve_fields >= REPO_FLOORS["serve_fields"]
    assert model.n_profile_knobs >= REPO_FLOORS["profile_knobs"]
    assert model.builders_found >= REPO_FLOORS["builders"]
    assert model.pin is not None, "KNOBS.json not loaded"
    assert model.changes == []


def test_registry_extraction_matches_import():
    """The AST-extracted registry IS the imported module's (the
    pure-literal contract): drift between the two would mean graftknob
    audits a phantom knob surface."""
    reg = load_repo_registry()
    assert reg.version == knobs.KNOBS_VERSION
    assert reg.knobs == knobs.KNOBS


def test_knobs_pin_matches_live_registry():
    pin = json.loads((REPO_ROOT / "KNOBS.json").read_text())
    assert pin == registry_to_pin(load_repo_registry())


def test_allowlist_is_live_and_shrink_only():
    """Every grandfather entry must still match a real finding: once
    the pattern is fixed, the entry MUST be deleted (shrink-only).
    The list is empty today — this keeps it honest if it ever grows."""
    findings, _ = analyze_paths(RUNTIME_PATHS, use_allowlist=False)
    for (suffix, key), why in ALLOWLIST.items():
        assert why.strip(), f"allowlist entry {key} needs a reason"
        assert any(
            f.path.replace("\\", "/").endswith(suffix) and f.key == key
            for f in findings
        ), (
            f"allowlist entry ({suffix}, {key}) matches no finding — "
            "the pattern was fixed; delete the entry"
        )


# ---------------------------------------------------------------------------
# The bump rule (--update-knobs)
# ---------------------------------------------------------------------------


def _add(detail="knob 'probe' added"):
    return PinChange("addition", "knob", "probe", detail)


def _rm(detail="knob 'probe' removed"):
    return PinChange("removal", "knob", "probe", detail)


def _meta(detail="note changed"):
    return PinChange("metadata", "knob", "lanes", detail)


def test_bump_rule():
    # additions need a minor (or major) bump
    assert check_bump("1.0", "1.0", [_add()]) is not None
    assert check_bump("1.0", "1.1", [_add()]) is None
    assert check_bump("1.0", "2.0", [_add()]) is None
    # removals/renames need a MAJOR bump — a minor does not satisfy
    assert check_bump("1.0", "1.1", [_rm()]) is not None
    assert check_bump("1.0", "2.0", [_rm()]) is None
    assert check_bump("1.0", "2.0", [_rm(), _add()]) is None
    # metadata-only re-pins need no bump but cannot move backwards
    assert check_bump("1.1", "1.1", [_meta()]) is None
    assert check_bump("1.1", "1.0", [_meta()]) is not None
    # the version-stamp pseudo-change never drives the rule
    v = PinChange("metadata", "version", "knobs_version", "1.0 -> 1.1")
    assert check_bump("1.0", "1.1", [v]) is None
    # unparseable versions are refused loudly
    with pytest.raises(ValueError):
        check_bump("banana", "1.0", [])


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_artifacts(tmp_path):
    """0 clean / 1 findings / 2 usage error through the real CLI, plus
    the --report/--metrics-json artifact shapes CI uploads."""
    report = tmp_path / "knobs.md"
    metrics = tmp_path / "metrics.json"
    clean = subprocess.run(
        [sys.executable, "-m", "tools.graftknob",
         *DEFAULT_PATHS,
         "--report", str(report), "--metrics-json", str(metrics)],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    md = report.read_text()
    assert "configuration-knob contract" in md
    assert "| knob | surfaces | default | roles | note |" in md
    payload = json.loads(metrics.read_text())["graftknob"]
    assert payload["findings"] == 0
    assert payload["knobs"] >= REPO_FLOORS["knobs"]
    assert payload["trace_sites"] >= REPO_FLOORS["trace_sites"]
    assert payload["pin_changes"] == 0
    usage = subprocess.run(
        [sys.executable, "-m", "tools.graftknob", "--select", "GK999"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert usage.returncode == 2


@pytest.mark.parametrize("code", CODES)
def test_cli_flags_every_fixture(code):
    """Each doctored fixture exits 1 through the real CLI with its
    code in stdout — the acceptance contract, not just the API."""
    cmd = [sys.executable, "-m", "tools.graftknob",
           "--select", code, *_fixture_paths(code, "flag")]
    if code == "GK006":
        cmd += ["--knobs-json", GK006_PIN]
    proc = subprocess.run(
        cmd, cwd=str(REPO_ROOT), capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert code in proc.stdout


def test_readme_knob_section_is_fresh(tmp_path):
    """The committed README section matches the live registry (the CI
    staleness gate as a test), and a doctored section actually fails —
    the check is not vacuous."""
    fresh = subprocess.run(
        [sys.executable, "-m", "tools.graftknob",
         "--select", "GK006", "--check-readme", "README.md"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert fresh.returncode == 0, fresh.stdout + fresh.stderr
    stale_md = tmp_path / "README.md"
    stale_md.write_text(
        (REPO_ROOT / "README.md").read_text().replace(
            "| `A5GEN_REFUSE` |", "| `A5GEN_REFUZE` |"
        )
    )
    stale = subprocess.run(
        [sys.executable, "-m", "tools.graftknob",
         "--select", "GK006", "--check-readme", str(stale_md)],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert stale.returncode == 1
    assert "stale" in stale.stderr

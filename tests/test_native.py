"""Native C++ scanner/packer vs the numpy reference: bit-identical outputs
on every line-structure edge, the anti-Q8 error, and the fallback path."""

import os
import pathlib

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu import native
from hashcat_a5_table_generator_tpu.ops.packing import (
    pack_words,
    read_wordlist,
    read_wordlist_lines,
)

CASES = [
    b"",
    b"\n",
    b"abc\n",
    b"abc",  # unterminated tail
    b"abc\r\n",  # CRLF
    b"abc\rx\n",  # interior CR preserved
    b"one\ntwo\nthree\n",
    b"\n\nmid\n\n",  # empty lines
    b"word\r\nmixed\nendings\r\n",
    bytes(range(1, 10)) + b"\n" + b"\xf0\x9f\x94\x91\n",  # binary + UTF-8
    b"a" * 100 + b"\n" + b"b\n",
]


def _native_or_skip():
    if not native.available():
        pytest.skip("native toolchain unavailable")


class TestScanParity:
    @pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
    def test_scan_matches_numpy(self, data):
        _native_or_skip()
        buf_n, off_n, len_n = native.scan_wordlist_bytes(data)
        buf_p, off_p, len_p = read_wordlist_lines(data)
        np.testing.assert_array_equal(off_n, off_p)
        np.testing.assert_array_equal(len_n, len_p)

    @pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
    def test_scan_matches_read_wordlist(self, data, tmp_path):
        # The line-structure view must reconstruct exactly the word list
        # the list-of-bytes reader produces.
        p = tmp_path / "w.txt"
        p.write_bytes(data)
        words = read_wordlist(str(p))
        buf, off, lens = read_wordlist_lines(data)
        got = [bytes(buf[o : o + l]) for o, l in zip(off, lens)]
        assert got == words

    def test_oversized_line_raises_both_paths(self):
        data = b"x" * 64 + b"\nok\n"
        with pytest.raises(ValueError, match="Q8"):
            read_wordlist_lines(data, max_word_bytes=10)
        _native_or_skip()
        with pytest.raises(ValueError, match="Q8"):
            native.scan_wordlist_bytes(data, max_word_bytes=10)


class TestPackParity:
    def test_read_packed_matches_pack_words(self, tmp_path):
        _native_or_skip()
        words = [b"password", b"", b"x" * 31, b"\xd0\xb9ob", b"tail"]
        p = tmp_path / "w.txt"
        p.write_bytes(b"\n".join(words) + b"\n")
        got = native.read_packed(str(p))
        want = pack_words(words)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_array_equal(got.lengths, want.lengths)
        np.testing.assert_array_equal(got.index, want.index)

    def test_selection_pack(self, tmp_path):
        _native_or_skip()
        data = b"aa\nbbbb\ncc\ndddddd\n"
        buf, off, lens = native.scan_wordlist_bytes(data)
        sel = np.asarray([1, 3], dtype=np.int64)
        got = native.pack_rows(buf, off, lens, sel, 8)
        want = pack_words([b"bbbb", b"dddddd"], width=8)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_array_equal(got.lengths, want.lengths)
        np.testing.assert_array_equal(got.index, sel)

    def test_width_overflow_errors(self):
        _native_or_skip()
        buf, off, lens = native.scan_wordlist_bytes(b"toolong\n")
        with pytest.raises(ValueError):
            native.pack_rows(buf, off, lens, None, 4)


class TestFallback:
    def test_forced_fallback_matches(self, tmp_path, monkeypatch):
        # A5_NATIVE=0 must produce identical results through the same API.
        p = tmp_path / "w.txt"
        p.write_bytes(b"alpha\nbeta\r\ngamma")
        import importlib

        import hashcat_a5_table_generator_tpu.native as nat

        monkeypatch.setenv("A5_NATIVE", "0")
        importlib.reload(nat)
        try:
            got = nat.read_packed(str(p))
            want = pack_words([b"alpha", b"beta", b"gamma"])
            np.testing.assert_array_equal(got.tokens, want.tokens)
            np.testing.assert_array_equal(got.lengths, want.lengths)
            assert nat.available() is False
        finally:
            monkeypatch.delenv("A5_NATIVE")
            importlib.reload(nat)


def test_native_builds_here():
    # This environment ships g++ (per the build brief); the native path must
    # actually engage in CI here, not silently fall back.
    assert native.available()


class TestNativeDefaultOracle:
    """The C++ engine-A oracle must be byte-for-byte identical to
    oracle.engines.process_word — stream order, duplicates (Q7),
    longest-first probing (Q5), no-rematch-of-replacement (Q6), window
    edges, binary bytes, length-changing values."""

    TABLES = [
        {b"a": [b"4", b"@"], b"s": [b"$", b"5"], b"e": [b"3"]},
        {b"ss": [b"\xc3\x9f"], b"s": [b"z"], b"a": [b"\xc3\xa4"]},
        {b"a": [b"4", b"4"]},                      # duplicate options (Q7)
        {b"ab": [b"X"], b"b": [b"Y"], b"a": [b"Z"]},  # overlap, longest-first
        {b"a": [b""], b"b": [b"bb"]},              # shrink + grow values
        {b"\x00": [b"\xff"], b"\xff\xfe": [b"\x00\x01"]},  # raw bytes
        {b"a": [b"ba"]},                           # value contains a key
    ]
    WORDS = [b"", b"x", b"glass", b"assassin", b"abab", b"aaaa",
             b"\x00\xff\xfe\x00", b"banana"]

    def _engine(self, sub):
        from hashcat_a5_table_generator_tpu.native.oracle_engine import (
            NativeDefaultOracle,
            available,
        )

        if not available():
            pytest.skip("no native toolchain")
        return NativeDefaultOracle(sub)

    @pytest.mark.parametrize("ti", range(7))
    def test_stream_parity(self, ti):
        import io

        from hashcat_a5_table_generator_tpu.oracle.engines import (
            process_word,
        )

        sub = self.TABLES[ti]
        eng = self._engine(sub)
        for word in self.WORDS:
            for lo, hi in [(0, 15), (1, 1), (2, 3), (0, 0), (3, 2)]:
                want = b"".join(
                    c + b"\n" for c in process_word(word, sub, lo, hi)
                )
                got = io.BytesIO()
                n = eng.stream_word(word, lo, hi, got.write)
                assert got.getvalue() == want, (ti, word, lo, hi)
                assert n == want.count(b"\n")

    def test_cli_fast_path_matches_python(self, tmp_path, monkeypatch):
        """The CLI's native fast path and the Python loop emit identical
        bytes (A5_NATIVE toggles the engine, never the stream)."""
        import subprocess
        import sys as _sys

        table = tmp_path / "t.table"
        table.write_bytes(b"a=4\na=@\ns=$\nss=\xc3\x9f\n")
        dict_file = tmp_path / "d.txt"
        dict_file.write_bytes(b"glass\nassassin\nsassy\n")
        driver = (
            "import sys\n"
            "from hashcat_a5_table_generator_tpu.cli import main\n"
            "sys.exit(main(sys.argv[1:]))"
        )
        outs = {}
        for nat in ("1", "0"):
            env = dict(os.environ)
            env["A5_NATIVE"] = nat
            env["PYTHONPATH"] = (
                str(pathlib.Path(__file__).resolve().parent.parent)
                + os.pathsep + env.get("PYTHONPATH", "")
            )
            r = subprocess.run(
                [_sys.executable, "-c", driver, str(dict_file),
                 "-t", str(table), "--backend", "oracle"],
                env=env, capture_output=True, timeout=120,
            )
            assert r.returncode == 0, r.stderr[-800:]
            outs[nat] = r.stdout
        assert outs["1"] == outs["0"]
        assert outs["1"].count(b"\n") > 10

    def test_eligibility_gate(self):
        from hashcat_a5_table_generator_tpu.cli import (
            native_default_eligible,
        )

        sub = {b"a": [b"4"]}
        assert native_default_eligible(sub, "default", False, False)
        assert not native_default_eligible(sub, "default", True, False)
        assert not native_default_eligible(sub, "default", False, True)
        assert native_default_eligible(sub, "suball", False, False)
        assert not native_default_eligible(sub, "reverse", False, False)
        # suball-reverse has no Q3 bug to model: native-eligible.
        assert native_default_eligible(sub, "suball-reverse", False, False)
        assert not native_default_eligible(
            {b"a": [b"\n"]}, "default", False, False
        )
        # Pathological windows keep the Python engine (native stack cap).
        assert not native_default_eligible(
            sub, "default", False, False, 100000
        )


class TestNativeSuballOracle:
    """Engine C (substitute-all) native parity: byte-for-byte against
    process_word_substitute_all across cascade interactions, empty
    keys/values, window edges, and the per-candidate iterator the
    sweep's hazard-fallback path consumes."""

    TABLES = [
        {b"a": [b"4", b"@"], b"s": [b"$"], b"e": [b"3"]},
        {b"ss": [b"\xc3\x9f"], b"s": [b"z"]},
        {b"a": [b""], b"": [b"Q"]},
        {b"a": [b"ba"], b"b": [b"ab"]},   # cascade interactions (Q4 order)
        {b"x": [b"y", b"y"]},             # duplicate options (Q7)
    ]
    WORDS = [b"", b"x", b"glass", b"assassin", b"abab", b"banana"]

    @pytest.mark.parametrize("ti", range(5))
    def test_stream_and_iter_parity(self, ti):
        import io

        from hashcat_a5_table_generator_tpu.native.oracle_engine import (
            NativeDefaultOracle,
            available,
        )
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            process_word_substitute_all,
        )

        if not available():
            pytest.skip("no native toolchain")
        sub = self.TABLES[ti]
        eng = NativeDefaultOracle(sub)
        for word in self.WORDS:
            for lo, hi in [(0, 15), (0, 0), (1, 2), (2, 2), (3, 1)]:
                want = list(process_word_substitute_all(word, sub, lo, hi))
                got = io.BytesIO()
                n = eng.stream_word_suball(word, lo, hi, got.write)
                assert got.getvalue() == b"".join(
                    c + b"\n" for c in want
                ), (ti, word, lo, hi)
                assert n == len(want)
                assert list(eng.iter_word(word, lo, hi,
                                          substitute_all=True)) == want

    def test_sweep_fallback_uses_native_and_matches(self):
        """A hazard table routes words through the oracle fallback; the
        sweep's candidate stream (native iterator) must equal the pure
        Python sweep's (A5_NATIVE path toggled via monkeypatched cache)."""
        import io

        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.runtime.sinks import (
            CandidateWriter,
        )
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        from hashcat_a5_table_generator_tpu.native.oracle_engine import (
            available,
        )

        if not available():
            pytest.skip("no native toolchain")
        # german-style hazard: ss -> ß while s -> z cascades.
        sub = {b"ss": [b"\xc3\x9f"], b"s": [b"z"], b"a": [b"4"]}
        words = [b"glass", b"assess", b"sassy"]
        spec = AttackSpec(mode="suball", algo="md5")
        cfg = SweepConfig(lanes=64, num_blocks=16)

        native_engaged = []

        def run(native: bool):
            sweep = Sweep(spec, sub, words, (), config=cfg)
            if not native:
                sweep._native_oracle_cache = None  # force Python engines
            assert sweep.fallback_rows  # the hazard actually routes
            buf = io.BytesIO()
            w = CandidateWriter(buf)
            sweep.run_candidates(w, resume=False)
            w.flush()
            if native:
                native_engaged.append(sweep._native_oracle_cache)
            return buf.getvalue()

        assert run(True) == run(False)
        # The native path must have actually engaged, not fallen back.
        assert native_engaged and native_engaged[0] is not None


def test_oracle_crack_native_matches_python(tmp_path):
    """Oracle crack mode fed by the native iterator must print the same
    hit lines as the pure-Python engines (A5_NATIVE toggles)."""
    import hashlib
    import subprocess
    import sys as _sys

    from hashcat_a5_table_generator_tpu.oracle.engines import (
        process_word_substitute_all,
    )

    table = tmp_path / "t.table"
    table.write_bytes(b"a=4\ns=$\nss=\xc3\x9f\n")
    dict_file = tmp_path / "d.txt"
    words = [b"glass", b"assassin", b"sassy"]
    dict_file.write_bytes(b"\n".join(words) + b"\n")
    sub = {b"a": [b"4"], b"s": [b"$"], b"ss": [b"\xc3\x9f"]}
    cands = []
    for w in words:
        cands.extend(process_word_substitute_all(w, sub, 0, 15))
    planted = sorted({cands[1], cands[-1]})
    digests = tmp_path / "digs.txt"
    digests.write_bytes(b"".join(
        hashlib.md5(c).digest().hex().encode() + b"\n" for c in planted
    ))
    driver = ("import sys\nfrom hashcat_a5_table_generator_tpu.cli import "
              "main\nsys.exit(main(sys.argv[1:]))")
    outs = {}
    for nat in ("1", "0"):
        env = dict(os.environ)
        env["A5_NATIVE"] = nat
        env["PYTHONPATH"] = (
            str(pathlib.Path(__file__).resolve().parent.parent)
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        r = subprocess.run(
            [_sys.executable, "-c", driver, str(dict_file), "-t",
             str(table), "-s", "--backend", "oracle",
             "--digests", str(digests)],
            env=env, capture_output=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-800:]
        outs[nat] = r.stdout
    assert outs["1"] == outs["0"]
    # >= not ==: convergent choice paths re-emit candidates (Q7), and
    # each emission of a planted candidate prints a hit line.
    assert outs["1"].count(b":") >= len(planted)
    got_plains = {ln.split(b":", 1)[1] for ln in outs["1"].splitlines()}
    assert got_plains == set(planted)


def test_native_engines_fuzz_parity():
    """Randomized tables/words (binary bytes, multichar keys, empty and
    multibyte values, duplicate options): all three native engines must
    match the Python anchor byte-for-byte on every sample."""
    import io
    import random

    from hashcat_a5_table_generator_tpu.native.oracle_engine import (
        NativeDefaultOracle,
        available,
    )
    from hashcat_a5_table_generator_tpu.oracle.engines import (
        process_word,
        process_word_substitute_all,
        process_word_substitute_all_reverse,
    )

    if not available():
        pytest.skip("no native toolchain")
    rng = random.Random(1234)
    alpha = b"abcx\x00\xff"

    def rand_bytes(lo, hi):
        return bytes(rng.choice(alpha) for _ in range(rng.randint(lo, hi)))

    for trial in range(40):
        sub = {}
        for _ in range(rng.randint(1, 5)):
            key = rand_bytes(1, 3)
            sub[key] = [rand_bytes(0, 3)
                        for _ in range(rng.randint(1, 3))]
        eng = NativeDefaultOracle(sub)
        for _ in range(4):
            word = rand_bytes(0, 7)
            lo = rng.randint(0, 3)
            hi = rng.randint(0, 5)
            want_a = b"".join(
                c + b"\n" for c in process_word(word, sub, lo, hi)
            )
            got = io.BytesIO()
            eng.stream_word(word, lo, hi, got.write)
            assert got.getvalue() == want_a, (trial, sub, word, lo, hi)
            want_c = b"".join(
                c + b"\n"
                for c in process_word_substitute_all(word, sub, lo, hi)
            )
            got = io.BytesIO()
            eng.stream_word_suball(word, lo, hi, got.write)
            assert got.getvalue() == want_c, (trial, sub, word, lo, hi)
            want_d = b"".join(
                c + b"\n"
                for c in process_word_substitute_all_reverse(
                    word, sub, lo, hi
                )
            )
            got = io.BytesIO()
            eng.stream_word_suball_reverse(word, lo, hi, got.write)
            assert got.getvalue() == want_d, (trial, sub, word, lo, hi)


class TestNativeSuballReverse:
    """Engine D (substitute-all reverse) native parity: byte-for-byte
    against process_word_substitute_all_reverse — subset order, Q2
    first-option, optionless patterns counting toward the floor."""

    TABLES = [
        {b"a": [b"4", b"@"], b"s": [b"$"], b"e": [b"3"]},
        {b"ss": [b"\xc3\x9f"], b"s": [b"z"]},
        {b"a": [b""], b"": [b"Q"]},
        {b"a": [b"ba"], b"b": [b"ab"]},
    ]
    WORDS = [b"", b"x", b"glass", b"assassin", b"abab", b"banana"]

    @pytest.mark.parametrize("ti", range(4))
    def test_stream_parity(self, ti):
        import io

        from hashcat_a5_table_generator_tpu.native.oracle_engine import (
            NativeDefaultOracle,
            available,
        )
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            process_word_substitute_all_reverse,
        )

        if not available():
            pytest.skip("no native toolchain")
        sub = self.TABLES[ti]
        eng = NativeDefaultOracle(sub)
        for word in self.WORDS:
            for lo, hi in [(0, 15), (0, 0), (1, 2), (2, 2), (3, 1)]:
                want = b"".join(
                    c + b"\n"
                    for c in process_word_substitute_all_reverse(
                        word, sub, lo, hi
                    )
                )
                got = io.BytesIO()
                n = eng.stream_word_suball_reverse(word, lo, hi, got.write)
                assert got.getvalue() == want, (ti, word, lo, hi)
                assert n == want.count(b"\n")
                assert list(eng.iter_word(
                    word, lo, hi, substitute_all=True, reverse=True
                )) == want.split(b"\n")[:-1]

"""Native C++ scanner/packer vs the numpy reference: bit-identical outputs
on every line-structure edge, the anti-Q8 error, and the fallback path."""

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu import native
from hashcat_a5_table_generator_tpu.ops.packing import (
    pack_words,
    read_wordlist,
    read_wordlist_lines,
)

CASES = [
    b"",
    b"\n",
    b"abc\n",
    b"abc",  # unterminated tail
    b"abc\r\n",  # CRLF
    b"abc\rx\n",  # interior CR preserved
    b"one\ntwo\nthree\n",
    b"\n\nmid\n\n",  # empty lines
    b"word\r\nmixed\nendings\r\n",
    bytes(range(1, 10)) + b"\n" + b"\xf0\x9f\x94\x91\n",  # binary + UTF-8
    b"a" * 100 + b"\n" + b"b\n",
]


def _native_or_skip():
    if not native.available():
        pytest.skip("native toolchain unavailable")


class TestScanParity:
    @pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
    def test_scan_matches_numpy(self, data):
        _native_or_skip()
        buf_n, off_n, len_n = native.scan_wordlist_bytes(data)
        buf_p, off_p, len_p = read_wordlist_lines(data)
        np.testing.assert_array_equal(off_n, off_p)
        np.testing.assert_array_equal(len_n, len_p)

    @pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
    def test_scan_matches_read_wordlist(self, data, tmp_path):
        # The line-structure view must reconstruct exactly the word list
        # the list-of-bytes reader produces.
        p = tmp_path / "w.txt"
        p.write_bytes(data)
        words = read_wordlist(str(p))
        buf, off, lens = read_wordlist_lines(data)
        got = [bytes(buf[o : o + l]) for o, l in zip(off, lens)]
        assert got == words

    def test_oversized_line_raises_both_paths(self):
        data = b"x" * 64 + b"\nok\n"
        with pytest.raises(ValueError, match="Q8"):
            read_wordlist_lines(data, max_word_bytes=10)
        _native_or_skip()
        with pytest.raises(ValueError, match="Q8"):
            native.scan_wordlist_bytes(data, max_word_bytes=10)


class TestPackParity:
    def test_read_packed_matches_pack_words(self, tmp_path):
        _native_or_skip()
        words = [b"password", b"", b"x" * 31, b"\xd0\xb9ob", b"tail"]
        p = tmp_path / "w.txt"
        p.write_bytes(b"\n".join(words) + b"\n")
        got = native.read_packed(str(p))
        want = pack_words(words)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_array_equal(got.lengths, want.lengths)
        np.testing.assert_array_equal(got.index, want.index)

    def test_selection_pack(self, tmp_path):
        _native_or_skip()
        data = b"aa\nbbbb\ncc\ndddddd\n"
        buf, off, lens = native.scan_wordlist_bytes(data)
        sel = np.asarray([1, 3], dtype=np.int64)
        got = native.pack_rows(buf, off, lens, sel, 8)
        want = pack_words([b"bbbb", b"dddddd"], width=8)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_array_equal(got.lengths, want.lengths)
        np.testing.assert_array_equal(got.index, sel)

    def test_width_overflow_errors(self):
        _native_or_skip()
        buf, off, lens = native.scan_wordlist_bytes(b"toolong\n")
        with pytest.raises(ValueError):
            native.pack_rows(buf, off, lens, None, 4)


class TestFallback:
    def test_forced_fallback_matches(self, tmp_path, monkeypatch):
        # A5_NATIVE=0 must produce identical results through the same API.
        p = tmp_path / "w.txt"
        p.write_bytes(b"alpha\nbeta\r\ngamma")
        import importlib

        import hashcat_a5_table_generator_tpu.native as nat

        monkeypatch.setenv("A5_NATIVE", "0")
        importlib.reload(nat)
        try:
            got = nat.read_packed(str(p))
            want = pack_words([b"alpha", b"beta", b"gamma"])
            np.testing.assert_array_equal(got.tokens, want.tokens)
            np.testing.assert_array_equal(got.lengths, want.lengths)
            assert nat.available() is False
        finally:
            monkeypatch.delenv("A5_NATIVE")
            importlib.reload(nat)


def test_native_builds_here():
    # This environment ships g++ (per the build brief); the native path must
    # actually engage in CI here, not silently fall back.
    assert native.available()

"""Layout emitter golden tests: the built-in layout maps must regenerate the
upstream ``.table`` artifacts byte-identically (BASELINE.json configs[0] —
"emit qwerty-azerty.table from built-in layout maps")."""

import pytest

from hashcat_a5_table_generator_tpu.tables.layouts import (
    BUILTIN_LAYOUTS,
    DERIVED_LAYOUTS,
    get_layout,
)
from hashcat_a5_table_generator_tpu.tables.parser import parse_substitution_table


@pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
def test_emitter_byte_identical_to_upstream(name, upstream_reference):
    artifact = upstream_reference / f"{name}.table"
    assert artifact.exists(), f"upstream artifact {name}.table missing"
    assert BUILTIN_LAYOUTS[name].to_table_bytes() == artifact.read_bytes()


@pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
def test_emitted_tables_parse_to_same_map(name):
    layout = BUILTIN_LAYOUTS[name]
    parsed = parse_substitution_table(layout.to_table_bytes())
    assert parsed == layout.to_substitution_map()


def test_azerty_qwerty_derivable():
    # README.MD:112,147,154 reference azerty-qwerty.table but never check it
    # in; inversion derives it.
    inv = get_layout("azerty-qwerty")
    fwd = get_layout("qwerty-azerty")
    assert inv.pairs == tuple((v, k) for k, v in fwd.pairs)
    # round-trips through the parser
    parsed = parse_substitution_table(inv.to_table_bytes())
    # 'q=a' and the case pair 'Q=a' both invert to key 'a', in pair order
    assert parsed[b"a"] == [b"q", b"Q"]


def test_inversion_involution():
    layout = get_layout("qwerty-greek")
    assert layout.inverted().inverted().pairs == layout.pairs


def test_cyrillic_multi_option_preserved_in_order():
    m = get_layout("qwerty-cyrillic").to_substitution_map()
    assert m[b";"] == ["ж".encode(), "Ж".encode()]


def test_unknown_layout_raises():
    with pytest.raises(KeyError):
        get_layout("dvorak-martian")


def test_derived_registry_names():
    assert set(DERIVED_LAYOUTS) == {
        "cyrillic-qwerty", "greek-qwerty", "hebrew-greek", "azerty-qwerty",
    }


def test_hex_escaping_round_trip():
    from hashcat_a5_table_generator_tpu.tables.layouts import Layout

    layout = Layout("weird", pairs=(("=", " x "), ("#c", "ok"), ("a", "b")))
    parsed = parse_substitution_table(layout.to_table_bytes())
    assert parsed == {b"=": [b" x "], b"#c": [b"ok"], b"a": [b"b"]}

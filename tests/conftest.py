"""Test configuration: force JAX onto CPU with 8 virtual devices so the
sharding / collective paths (pjit, shard_map, all_gather over a Mesh) are
exercised without TPU hardware (SURVEY.md §4.3). Must run before jax imports."""

import os

# Force, don't setdefault: the driver environment pre-sets JAX_PLATFORMS=axon
# (the remote-TPU tunnel), and every dispatch over the tunnel costs a network
# round trip — the suite must run on the local CPU backend regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The jaxtyping pytest plugin imports jax BEFORE this conftest runs, and
# jax_platforms is snapshotted from the env at import time — so the env vars
# above came too late and the suite would silently run over the TPU tunnel.
# jax.config.update overrides the snapshot (the backend itself has not been
# initialized yet at conftest time, so the switch is still safe).
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

# Persistent XLA compilation cache: the unrolled hash kernels take tens of
# seconds to compile cold; cached, the suite runs in seconds.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

# Hermeticity (PERF.md §29): a developer's ~/.cache/a5gen autotune
# profile must never change test results — geometry left to the runtime
# resolves to built-in defaults here.  Tests exercising profile loading
# point A5GEN_TUNE_PROFILE at their own tmp directory via monkeypatch.
os.environ["A5GEN_TUNE_PROFILE"] = "off"

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
UPSTREAM_REFERENCE = pathlib.Path("/root/reference")

#: Cached 2-process pod collectives capability (None = not probed yet).
_POD_COLLECTIVES: "bool | None" = None

_POD_PROBE_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # one local device per process
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from hashcat_a5_table_generator_tpu.parallel import multihost
pid = int(sys.argv[1])
multihost.initialize(f"127.0.0.1:{sys.argv[2]}", 2, pid)
from jax.experimental.multihost_utils import process_allgather
got = process_allgather(np.asarray([pid], np.int32))
assert sorted(np.asarray(got).reshape(-1).tolist()) == [0, 1], got
print("POD-OK")
"""


def pod_collectives_supported() -> bool:
    """Whether THIS host can run a real 2-process ``jax.distributed``
    pod with cross-process collectives.  CPU backends on the pinned jax
    fail inside ``process_allgather`` with "Multiprocess computations
    aren't implemented on the CPU backend" — an environment capability,
    not a code regression — so the 2-process pod tests skip (not fail)
    there, keeping the tier-1 DOTS_PASSED signal clean.  On backends
    with real collectives the probe passes and the tests run.  One
    probe per session (two tiny subprocesses), run lazily by the
    ``pod_collectives`` fixture only when a pod test is selected."""
    global _POD_COLLECTIVES
    if _POD_COLLECTIVES is not None:
        return _POD_COLLECTIVES
    import socket
    import subprocess
    import sys
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix=".py", delete=False
    ) as fh:
        fh.write(_POD_PROBE_CHILD)
        script = fh.name
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(p), str(port)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for p in range(2)
        ]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=120)
                outs.append((p.returncode, out, err))
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append((1, b"", b""))
        if all(rc == 0 and b"POD-OK" in out for rc, out, _e in outs):
            _POD_COLLECTIVES = True
        else:
            # Only the KNOWN capability error downgrades to a skip; any
            # other probe failure (a regression in multihost.initialize,
            # a transient port race, a hang) reports SUPPORTED so the
            # real pod tests run and fail loudly instead of being
            # masked by a green skip.
            _POD_COLLECTIVES = not any(
                b"implemented on the CPU backend" in err
                for _rc, _out, err in outs
            )
    finally:
        os.unlink(script)
    return _POD_COLLECTIVES


@pytest.fixture
def pod_collectives():
    """Backend-capability guard for real 2-process pod tests: skip —
    never fail — where multi-process collectives don't exist (the CPU
    backend; see :func:`pod_collectives_supported`)."""
    if not pod_collectives_supported():
        pytest.skip(
            "2-process pod collectives unavailable on this backend "
            "(process_allgather: multiprocess computations aren't "
            "implemented on the CPU backend)"
        )


@pytest.hookimpl(trylast=True)
def pytest_collection_modifyitems(config, items):
    """Tier-1 budget guard (CI sets ``A5GEN_FORBID_SLOW=1``): the tier-1
    command deselects ``slow`` tests via ``-m 'not slow'``; if that filter
    ever drifts (dropped flag, edited expression), slow-marked tests
    silently join the default collection and blow the 870 s budget.
    Under the env flag, any SELECTED item carrying the marker is a hard
    collection error — the regression surfaces in CI before it bites.
    Local full-suite runs (env unset) are unaffected.

    ``trylast``: the mark plugin's own (trylast) deselection hook runs
    before this conftest one, so ``items`` here is the post-filter
    selection — with the filter intact the guard sees no slow items."""
    from hashcat_a5_table_generator_tpu.runtime.env import env_is

    if not env_is("A5GEN_FORBID_SLOW", "1"):
        return
    leaked = [item.nodeid for item in items
              if item.get_closest_marker("slow") is not None]
    if leaked:
        raise pytest.UsageError(
            "A5GEN_FORBID_SLOW=1: slow-marked tests are in the selected "
            "set (the tier-1 '-m not slow' filter has drifted): "
            + ", ".join(leaked[:5])
            + (f" ... +{len(leaked) - 5} more" if len(leaked) > 5 else "")
        )


@pytest.fixture(scope="session")
def reference_tables(tmp_path_factory) -> pathlib.Path:
    """Directory of parity-fixture ``.table`` files, regenerated from the
    built-in layout maps by the emitter (golden-tested byte-identical to the
    upstream artifacts in tests/test_layouts.py)."""
    from hashcat_a5_table_generator_tpu.tables.layouts import (
        BUILTIN_LAYOUTS,
        emit_table,
    )

    tables_dir = tmp_path_factory.mktemp("tables")
    for name, layout in BUILTIN_LAYOUTS.items():
        emit_table(layout, str(tables_dir / f"{name}.table"))
    return tables_dir


@pytest.fixture(scope="session")
def upstream_reference() -> pathlib.Path:
    """The read-only upstream snapshot, when present (for golden byte checks)."""
    if not UPSTREAM_REFERENCE.is_dir():
        pytest.skip("upstream reference snapshot not available")
    return UPSTREAM_REFERENCE


@pytest.fixture
def compile_watcher():
    """Factory for :class:`tools.graftlint.runtime.CompileWatcher`:
    counts JAX compilation-cache misses around hot-path regions and
    fails on cache-busting argument signatures (see
    tests/test_compile_cache.py)."""
    import sys

    sys.path.insert(0, str(REPO_ROOT))
    try:
        from tools.graftlint.runtime import CompileWatcher
    finally:
        sys.path.pop(0)

    return CompileWatcher

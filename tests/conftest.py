"""Test configuration: force JAX onto CPU with 8 virtual devices so the
sharding / collective paths (pjit, shard_map, all_gather over a Mesh) are
exercised without TPU hardware (SURVEY.md §4.3). Must run before jax imports."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pathlib

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
UPSTREAM_REFERENCE = pathlib.Path("/root/reference")


@pytest.fixture(scope="session")
def reference_tables(tmp_path_factory) -> pathlib.Path:
    """Directory of parity-fixture ``.table`` files, regenerated from the
    built-in layout maps by the emitter (golden-tested byte-identical to the
    upstream artifacts in tests/test_layouts.py)."""
    from hashcat_a5_table_generator_tpu.tables.layouts import (
        BUILTIN_LAYOUTS,
        emit_table,
    )

    tables_dir = tmp_path_factory.mktemp("tables")
    for name, layout in BUILTIN_LAYOUTS.items():
        emit_table(layout, str(tables_dir / f"{name}.table"))
    return tables_dir


@pytest.fixture(scope="session")
def upstream_reference() -> pathlib.Path:
    """The read-only upstream snapshot, when present (for golden byte checks)."""
    if not UPSTREAM_REFERENCE.is_dir():
        pytest.skip("upstream reference snapshot not available")
    return UPSTREAM_REFERENCE

"""Streaming plan pipeline (PERF.md §19): chunked ingestion must be
STREAM-INVISIBLE next to whole-dictionary materialization — hits by full
(word_index, rank, candidate) tuples, candidate streams byte-for-byte —
across match/suball (fallback interleave), windowed plans, words
straddling chunk boundaries, and 8-device sharding; fingerprints are
identical so checkpoints cross paths both ways, mid-chunk resume never
recompiles swept chunks, and resident plan memory is bounded by
ring × chunk.  Plus the ``A5GEN_STREAM`` escape hatch, the on-disk
PieceSchema cache, and the ``--stream-ab`` bench record shape
(slow-marked: it compiles and times a subprocess bench).
"""

import hashlib
import io
import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec, build_plan
from hashcat_a5_table_generator_tpu.ops.packing import (
    ChunkCompiler,
    PlanChunk,
    auto_chunk_words,
    chunk_bounds,
    load_piece_schema,
    pack_words,
    piece_schema_for,
    save_piece_schema,
    slice_packed,
)
from hashcat_a5_table_generator_tpu.runtime import (
    CandidateWriter,
    HitRecorder,
    Sweep,
    SweepConfig,
    load_checkpoint,
)
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from tests.test_superstep import LEET, WORDS, hit_tuples, oracle_lines

REPO = pathlib.Path(__file__).resolve().parent.parent


def make_sweep(spec, sub_map, words, digests=(), *, chunk, devices=1,
               **cfg_kw):
    cfg = SweepConfig(lanes=64, num_blocks=16, devices=devices,
                      stream_chunk_words=chunk, **cfg_kw)
    return Sweep(spec, sub_map, words, digests, config=cfg)


def run_crack(spec, sub_map, words, digests, *, chunk, devices=1, **cfg_kw):
    return make_sweep(
        spec, sub_map, words, digests, chunk=chunk, devices=devices,
        **cfg_kw
    ).run_crack()


def candidate_bytes(spec, sub_map, words, *, chunk, **cfg_kw):
    buf = io.BytesIO()
    with CandidateWriter(stream=buf) as writer:
        make_sweep(
            spec, sub_map, words, chunk=chunk, **cfg_kw
        ).run_candidates(writer)
    return buf.getvalue()


class TestStreamParity:
    """streaming == whole, bit for bit, on every mode the device runs."""

    # Tier-1 budget: the default tier keeps one fast representative per
    # claim; the heavier variants (second mode, windowed, 8-device,
    # cross-path resume) are slow-marked per the 870 s contract.
    @pytest.mark.parametrize("mode", [
        "default", pytest.param("suball", marks=pytest.mark.slow),
    ])
    def test_crack_hits_and_counts(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(40)]

        whole = run_crack(spec, LEET, WORDS, digests, chunk="off")
        streamed = run_crack(spec, LEET, WORDS, digests, chunk=2)
        assert streamed.n_emitted == whole.n_emitted == len(oracle)
        assert hit_tuples(streamed) == hit_tuples(whole)
        assert {h.candidate for h in streamed.hits} == set(planted)
        assert whole.stream == {}
        assert streamed.stream["chunks"] == 3
        assert streamed.stream["chunks_swept"] == 3

    @pytest.mark.slow  # ~11 s on the tier-1 host; the suball fallback
    # interleave keeps default coverage via the single-chunk fallback
    # arm above and the stream-parity tests.
    def test_suball_fallback_interleave_across_chunks(self):
        # Oracle-routed hazard words sit at chunk boundaries: the global
        # fallback bookkeeping (prescan) must interleave them exactly
        # where the whole-dictionary plan does.
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
        words = [b"zz", b"acb", b"za", b"zacb", b"azz"]
        spec = AttackSpec(mode="suball", algo="md5")
        fb_cand = oracle_lines(spec, sub, [b"acb"])[-1]
        dev_cand = oracle_lines(spec, sub, [b"azz"])[-1]
        digests = [hashlib.md5(fb_cand).digest(),
                   hashlib.md5(dev_cand).digest()]

        sweep = make_sweep(spec, sub, words, digests, chunk=2)
        assert sweep.fallback_rows, "fixture must exercise fallback"
        streamed = sweep.run_crack()
        whole = run_crack(spec, sub, words, digests, chunk="off")
        assert hit_tuples(streamed) == hit_tuples(whole)
        assert {h.candidate for h in streamed.hits} == {fb_cand, dev_cand}

    @pytest.mark.slow
    def test_windowed_plan_forced_globally(self):
        # The count-windowed decision is a BATCH-level gate; chunks must
        # inherit the global decision or ranks renumber mid-sweep.
        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=1)
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[0]).digest(),
                   hashlib.md5(oracle[-1]).digest()]
        whole_sweep = make_sweep(spec, LEET, WORDS, digests, chunk="off")
        assert whole_sweep.plan.windowed
        stream_sweep = make_sweep(spec, LEET, WORDS, digests, chunk=2)
        assert stream_sweep._stream["windowed"]
        assert stream_sweep.fingerprint == whole_sweep.fingerprint
        whole = whole_sweep.run_crack()
        streamed = stream_sweep.run_crack()
        assert hit_tuples(streamed) == hit_tuples(whole)
        assert streamed.n_emitted == whole.n_emitted == len(oracle)

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_candidates_byte_parity(self, mode):
        sub = (
            LEET if mode == "default"
            else {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
        )
        words = (
            WORDS if mode == "default"
            else [b"zz", b"acb", b"za", b"zacb", b"azz"]
        )
        spec = AttackSpec(mode=mode, algo="md5")
        whole = candidate_bytes(spec, sub, words, chunk="off")
        streamed = candidate_bytes(spec, sub, words, chunk=2)
        assert streamed == whole

    def test_boundary_straddling_bucket_words(self):
        # chunk=1: every word is its own chunk, and lanes=64 splits each
        # word's variant space across many launches — every boundary is
        # a chunk boundary AND a launch boundary.
        spec = AttackSpec(mode="default", algo="md5")
        whole = candidate_bytes(spec, LEET, WORDS, chunk="off")
        streamed = candidate_bytes(spec, LEET, WORDS, chunk=1)
        assert streamed == whole

    @pytest.mark.slow
    def test_eight_device_sharded_parity(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[1], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]

        streamed = run_crack(spec, LEET, WORDS, digests, chunk=3,
                             devices=8)
        whole = run_crack(spec, LEET, WORDS, digests, chunk="off",
                          devices=8)
        one = run_crack(spec, LEET, WORDS, digests, chunk=3)
        assert hit_tuples(streamed) == hit_tuples(whole) == hit_tuples(one)
        assert streamed.n_emitted == whole.n_emitted == one.n_emitted
        assert streamed.stream["chunks_swept"] == 2

    def test_auto_keeps_whole_path_for_small_dictionaries(self):
        # 'auto' engages only past one auto-sized chunk: a 5-word
        # dictionary stays on the whole path (it IS the chunk).
        spec = AttackSpec(mode="default", algo="md5")
        sweep = make_sweep(spec, LEET, WORDS,
                           [hashlib.md5(b"nope").digest()], chunk="auto")
        assert sweep._stream is None
        assert sweep.plan is not None
        assert auto_chunk_words(16) >= 1024

    def test_invalid_chunk_words_raises(self):
        spec = AttackSpec(mode="default", algo="md5")
        with pytest.raises(ValueError):
            make_sweep(spec, LEET, WORDS, (), chunk=0.5)


class TestStreamResume:
    def test_mid_chunk_resume_completes_identically(self, tmp_path):
        """A crash mid-dictionary leaves a plain global (word, rank)
        cursor plus the active-chunk marker; a streaming resume starts
        at the cursor's chunk (never recompiling swept ones) and the
        final hit list matches an uninterrupted run."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[3], oracle[-2]})
        digests = [hashlib.md5(c).digest() for c in planted]
        want = run_crack(spec, LEET, WORDS, digests, chunk=2)

        path = str(tmp_path / "stream.json")
        cfg_kw = dict(checkpoint_path=path, checkpoint_every_s=0.0,
                      superstep=1)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = make_sweep(spec, LEET, WORDS, digests, chunk=2, **cfg_kw)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None
        assert partial.cursor.word < len(WORDS)

        second = make_sweep(spec, LEET, WORDS, digests, chunk=2, **cfg_kw)
        got = second.run_crack()
        assert got.resumed
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )
        assert got.stream["resumed_chunk"] >= 0
        done = load_checkpoint(path, second.fingerprint)
        assert done.stream is not None
        assert done.stream["chunk_words"] == 2

    @pytest.mark.slow
    def test_cross_path_resume_round_trip(self, tmp_path):
        """streaming → whole → streaming: the fingerprint and the
        (word, rank) cursor are path-independent, so a streaming
        checkpoint resumes under whole-dictionary materialization and
        its checkpoint resumes back under streaming."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[1], oracle[len(oracle) // 2], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        path = str(tmp_path / "cross.json")
        cfg_kw = dict(checkpoint_path=path, checkpoint_every_s=0.0,
                      superstep=1)

        class Boom(Exception):
            pass

        def exploding(after):
            class R(HitRecorder):
                def emit(self, record):
                    super().emit(record)
                    if len(self.hits) >= after:
                        raise Boom()
            return R()

        with pytest.raises(Boom):
            make_sweep(spec, LEET, WORDS, digests, chunk=2,
                       **cfg_kw).run_crack(exploding(1))
        with pytest.raises(Boom):
            make_sweep(spec, LEET, WORDS, digests, chunk="off",
                       **cfg_kw).run_crack(exploding(2))
        got = make_sweep(spec, LEET, WORDS, digests, chunk=2,
                         **cfg_kw).run_crack()
        assert got.resumed
        want = run_crack(spec, LEET, WORDS, digests, chunk=2)
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )
        assert {h.candidate for h in got.hits} == set(planted)


class TestBoundedMemory:
    def test_resident_plan_bytes_bounded_by_ring(self):
        spec = AttackSpec(mode="default", algo="md5")
        digests = [hashlib.md5(b"nope").digest()]
        # superstep=0: the bound is about plan arrays, and the per-launch
        # path skips five per-chunk superstep compiles (tier-1 budget).
        res = run_crack(spec, LEET, WORDS, digests, chunk=1, superstep=0)
        s = res.stream
        assert s["chunks_swept"] == len(WORDS)
        assert s["chunk_bytes_max"] > 0
        # The bounded-memory contract: the chunk being swept + the
        # prefetch window + one compile in flight — NEVER the whole
        # dictionary's plan.
        assert (
            s["peak_resident_plan_bytes"]
            <= s["ring"] * s["chunk_bytes_max"]
        )

    def test_compiler_ring_caps_outstanding_chunks(self):
        peak = [0]
        live = [0]

        def compile_fn(ci, lo, hi):
            live[0] += 1
            peak[0] = max(peak[0], live[0])

            def releaser(chunk):
                live[0] -= 1

            return PlanChunk(index=ci, lo=lo, hi=hi, releaser=releaser)

        bounds = chunk_bounds(10, 2)
        compiler = ChunkCompiler(compile_fn, bounds, prefetch=1)
        seen = []
        for chunk in compiler:
            seen.append((chunk.index, chunk.lo, chunk.hi))
            chunk.release()
        compiler.close()
        assert seen == [(i, lo, hi) for i, (lo, hi) in enumerate(bounds)]
        assert peak[0] <= 3  # swept + prefetched + one being compiled

    def test_compiler_propagates_worker_errors(self):
        def compile_fn(ci, lo, hi):
            raise RuntimeError("schema exploded")

        compiler = ChunkCompiler(compile_fn, chunk_bounds(4, 2))
        with pytest.raises(RuntimeError, match="schema exploded"):
            next(iter(compiler))
        compiler.close()


class TestEscapeHatches:
    def test_env_off_pins_whole_path(self, monkeypatch):
        monkeypatch.setenv("A5GEN_STREAM", "off")
        spec = AttackSpec(mode="default", algo="md5")
        sweep = make_sweep(spec, LEET, WORDS,
                           [hashlib.md5(b"nope").digest()], chunk=2)
        assert sweep._stream is None
        res = sweep.run_crack()
        assert res.stream == {}

    def test_env_typo_warns_and_keeps_default(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            stream_enabled,
        )

        monkeypatch.setenv("A5GEN_STREAM", "offf")
        assert stream_enabled()
        assert "A5GEN_STREAM" in capsys.readouterr().err


class TestSchemaCache:
    def _plan(self, words=(b"password", b"sesame")):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        return spec, ct, build_plan(spec, ct, pack_words(list(words)))

    def test_disk_roundtrip_hits_second_time(self, tmp_path, monkeypatch):
        cache = str(tmp_path / "schemas")
        _spec, ct, plan = self._plan()
        s1 = piece_schema_for(plan, ct, cache_dir=cache)
        assert s1 is not None
        files = list(pathlib.Path(cache).glob("*.npz"))
        assert len(files) == 1
        # Second, fresh plan over identical inputs must LOAD, not build:
        # break the builder to prove the hit.
        import hashcat_a5_table_generator_tpu.ops.packing as packing

        def boom(*a, **k):  # pragma: no cover - must never run
            raise AssertionError("cache miss: build_piece_schema ran")

        monkeypatch.setattr(packing, "build_piece_schema", boom)
        _spec2, ct2, plan2 = self._plan()
        s2 = piece_schema_for(plan2, ct2, cache_dir=cache)
        assert s2 is not None
        assert s2.groups == s1.groups
        assert s2.kind == s1.kind and s2.max_out == s1.max_out
        for name in ("gw", "gl", "gw16", "sel_bit", "sel_slot"):
            a, b = getattr(s1, name), getattr(s2, name)
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_array_equal(a, b)

    def test_ineligible_plan_refusal_is_cached_too(self, tmp_path):
        # Overlapping static spans refuse the schema; the (deterministic)
        # refusal is cached so repeat sweeps skip the walk.
        cache = str(tmp_path / "schemas")
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table({b"ab": [b"X"], b"b": [b"Y"]})
        plan = build_plan(spec, ct, pack_words([b"abab"]))
        assert piece_schema_for(plan, ct, cache_dir=cache) is None
        files = list(pathlib.Path(cache).glob("*.npz"))
        assert len(files) == 1
        plan2 = build_plan(spec, ct, pack_words([b"abab"]))
        assert piece_schema_for(plan2, ct, cache_dir=cache) is None

    def test_corrupt_entry_is_a_miss_not_an_error(self, tmp_path):
        cache = tmp_path / "schemas"
        cache.mkdir()
        (cache / ("ab" * 32 + ".npz")).write_bytes(b"not an npz")
        hit, schema = load_piece_schema(str(cache), "ab" * 32)
        assert hit is False and schema is None

    @pytest.mark.slow
    def test_sweep_config_threads_cache_dir(self, tmp_path):
        cache = str(tmp_path / "schemas")
        spec = AttackSpec(mode="default", algo="md5")
        res = run_crack(
            spec, LEET, WORDS, [hashlib.md5(b"nope").digest()],
            chunk=2, schema_cache=cache,
        )
        assert res.n_emitted > 0
        assert list(pathlib.Path(cache).glob("*.npz"))

    def test_gl_table_ships_dynamic_groups_only(self):
        # The §19 gl-slicing satellite: fixed-length groups never read a
        # length row, so the shipped table covers exactly the dynamic
        # groups (all-fixed schemas ship none).
        _spec, ct, plan = self._plan()
        schema = piece_schema_for(plan, ct)
        dyn = [g for g in schema.groups if g.len_fixed is None]
        if dyn:
            assert schema.gl is not None
            assert schema.gl.shape[1] == len(dyn)
            assert [g.gl_idx for g in dyn] == list(range(len(dyn)))
        else:  # pragma: no cover - fixture-dependent
            assert schema.gl is None
        # An all-fixed schema (single word, no substitutions varying
        # length) must ship no gl at all.
        spec = AttackSpec(mode="default", algo="md5")
        ct2 = compile_table({b"a": [b"X"]})  # same-length value
        plan2 = build_plan(spec, ct2, pack_words([b"banana"]))
        schema2 = piece_schema_for(plan2, ct2)
        assert schema2 is not None
        assert all(g.len_fixed is not None for g in schema2.groups)
        assert schema2.gl is None


def test_progress_json_carries_chunk_position(tmp_path):
    """CheckpointState.stream surfaces in the progress JSON: live
    streaming sweeps report their chunk marker per line, and a RESUMED
    streaming sweep seeds it immediately from the checkpoint."""
    from hashcat_a5_table_generator_tpu.runtime import ProgressReporter

    spec = AttackSpec(mode="default", algo="md5")
    oracle = oracle_lines(spec, LEET, WORDS)
    digests = [hashlib.md5(oracle[0]).digest()]
    buf = io.StringIO()
    prog = ProgressReporter(len(WORDS), every_s=0.0, stream=buf)
    path = str(tmp_path / "ck.json")
    res = make_sweep(
        spec, LEET, WORDS, digests, chunk=2, progress=prog,
        checkpoint_path=path, checkpoint_every_s=0.0,
    ).run_crack()
    markers = [
        json.loads(ln)["progress"].get("stream")
        for ln in buf.getvalue().splitlines()
    ]
    assert {"chunk": 0, "chunk_words": 2} in markers
    assert {"chunk": 2, "chunk_words": 2} in markers
    assert res.stream["chunks_swept"] == 3

    # Resume with a mid-stream checkpoint: the marker is seeded from
    # CheckpointState.stream before any chunk completes.
    sweep = make_sweep(spec, LEET, WORDS, digests, chunk=2,
                       checkpoint_path=path)
    state = load_checkpoint(path, sweep.fingerprint)
    state.stream = {"chunk": 1, "chunk_words": 2}
    buf2 = io.StringIO()
    prog2 = ProgressReporter(len(WORDS), every_s=0.0, stream=buf2)
    sweep.config.progress = prog2
    machine = sweep.crack_machine(state=state)
    try:
        next(machine)
    except StopIteration:
        pass
    machine.close()
    first = json.loads(buf2.getvalue().splitlines()[0])
    assert first["progress"]["stream"] == {"chunk": 1, "chunk_words": 2}


def test_slice_packed_keeps_global_indices():
    packed = pack_words(WORDS)
    part = slice_packed(packed, 2, 5)
    assert part.batch == 3
    assert list(part.index) == [2, 3, 4]
    assert part.word(0) == WORDS[2]


@pytest.mark.slow
def test_bench_stream_ab_record_shape():
    """The §19 measurement instrument: one JSON line, both arms, the
    ttfc/overlap/resident-bytes numbers the acceptance criteria read.
    Slow-marked: it compiles and times a subprocess bench."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--stream-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "2000"],
        capture_output=True, timeout=540, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "stream_ingestion_ab"
    assert rec["chunks"] >= 4
    assert rec["whole"]["n_emitted"] == rec["streaming"]["n_emitted"] > 0
    st = rec["streaming"]["stream"]
    assert st["chunks_swept"] == rec["chunks"]
    assert st["peak_resident_plan_bytes"] <= (
        st["ring"] * st["chunk_bytes_max"]
    )
    assert rec["ttfc_vs_chunk_compile"] > 0
    assert 0.0 <= rec["overlap_ratio"] <= 1.0
    assert 0.0 <= rec["steady_overlap_ratio"] <= 1.0
    for arm in ("whole", "streaming"):
        assert rec[arm]["ttfc_s"] > 0
        assert rec[arm]["wall_s"] >= rec[arm]["ttfc_s"]

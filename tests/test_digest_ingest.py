"""Vectorized digest-list ingest: the numpy left-list parser, the bulk
DigestSet build, and the matrix-form digest plumbing through the sweep
(hashmob-scale lists must not pay per-line/per-digest Python loops, and
the fast paths must be observationally identical to the loops)."""

import hashlib

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.cli import _read_digests
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set

DIGS = [hashlib.md5(b"word%d" % i).digest() for i in range(500)]


def _write(tmp_path, body: bytes):
    p = tmp_path / "left.txt"
    p.write_bytes(body)
    return str(p)


class TestVectorParser:
    def test_plain_lines_give_matrix(self, tmp_path):
        p = _write(tmp_path, b"".join(d.hex().encode() + b"\n" for d in DIGS))
        out = _read_digests(p, "md5")
        assert isinstance(out, np.ndarray) and out.shape == (500, 16)
        assert out.tobytes() == b"".join(DIGS)

    def test_suffixes_comments_blanks_crlf_upper(self, tmp_path):
        body = (
            b"# comment\n\n"
            + DIGS[0].hex().encode() + b":plain text\n"
            + DIGS[1].hex().upper().encode() + b"\r\n"
            + DIGS[2].hex().encode() + b":\n"
            + b"#" + DIGS[3].hex().encode() + b"\n"
            + DIGS[4].hex().encode()  # no trailing newline
        )
        out = _read_digests(_write(tmp_path, body), "md5")
        assert isinstance(out, np.ndarray)
        assert out.tobytes() == DIGS[0] + DIGS[1] + DIGS[2] + DIGS[4]

    def test_leading_whitespace_falls_back_to_loop(self, tmp_path):
        body = b"  " + DIGS[0].hex().encode() + b"\n"
        out = _read_digests(_write(tmp_path, body), "md5")
        assert isinstance(out, list) and out == [DIGS[0]]

    def test_bad_hex_raises_loop_message(self, tmp_path):
        body = DIGS[0].hex().encode() + b"\nzz" + DIGS[1].hex().encode()[2:] + b"\n"
        with pytest.raises(SystemExit, match=r"left.txt:2: not a hex digest"):
            _read_digests(_write(tmp_path, body), "md5")

    def test_wrong_length_raises_loop_message(self, tmp_path):
        body = DIGS[0].hex().encode() + b"\nabcdef\n"
        with pytest.raises(SystemExit, match=r"left.txt:2: 3-byte digest"):
            _read_digests(_write(tmp_path, body), "md5")

    def test_sha1_width(self, tmp_path):
        digs = [hashlib.sha1(b"w%d" % i).digest() for i in range(20)]
        p = _write(tmp_path, b"".join(d.hex().encode() + b"\n" for d in digs))
        out = _read_digests(p, "sha1")
        assert isinstance(out, np.ndarray) and out.shape == (20, 20)
        assert out.tobytes() == b"".join(digs)

    def test_empty_file(self, tmp_path):
        assert len(_read_digests(_write(tmp_path, b""), "md5")) == 0
        assert len(_read_digests(_write(tmp_path, b"\n# c\n"), "md5")) == 0


class TestBulkDigestSet:
    @pytest.mark.parametrize("algo,mk", [
        ("md5", lambda b: hashlib.md5(b).digest()),
        ("sha1", lambda b: hashlib.sha1(b).digest()),
    ])
    def test_matrix_list_hex_forms_identical(self, algo, mk):
        digs = [mk(b"x%d" % i) for i in range(300)] + [mk(b"x0")]  # dup
        mat = np.frombuffer(b"".join(digs), np.uint8).reshape(
            len(digs), -1
        )
        s_list = build_digest_set(digs, algo)
        s_mat = build_digest_set(mat, algo)
        s_hex = build_digest_set([d.hex() for d in digs], algo)
        assert (s_list.rows == s_mat.rows).all()
        assert (s_list.rows == s_hex.rows).all()
        assert (s_list.bitmap == s_mat.bitmap).all()
        assert s_list.size == 300  # dup collapsed

    def test_empty_matrix(self):
        s = build_digest_set(np.zeros((0, 16), np.uint8), "md5")
        assert s.size == 0


class TestMatrixDigestsThroughSweep:
    def test_crack_with_matrix_digests_matches_list(self, tmp_path):
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            iter_candidates,
        )
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        # german-style table gives cascade-hazard fallback words, so the
        # matrix path's host-side _digest_contains (fallback hits + device
        # re-verification) executes on both the device and oracle routes.
        sub = {b"a": [b"\xc3\xa4"], b"s": [b"$"], b"ss": [b"\xc3\x9f"]}
        words = [b"glass", b"pass", b"mass", b"lass"]
        spec = AttackSpec(mode="default", algo="md5")
        oracle = []
        for w in words:
            oracle.extend(iter_candidates(w, sub, 0, 15))
        planted = sorted({oracle[1], oracle[-1]})
        digs = [hashlib.md5(c).digest() for c in planted]
        digs += [hashlib.md5(b"decoy%d" % i).digest() for i in range(50)]
        mat = np.frombuffer(b"".join(digs), np.uint8).reshape(-1, 16)

        cfg = SweepConfig(lanes=64, num_blocks=16)
        res_list = Sweep(spec, sub, words, digs, config=cfg).run_crack()
        res_mat = Sweep(spec, sub, words, mat, config=cfg).run_crack()
        key = lambda h: (h.word_index, h.variant_rank)  # noqa: E731
        assert sorted(map(key, res_mat.hits)) == sorted(
            map(key, res_list.hits)
        )
        assert {h.candidate for h in res_mat.hits} == set(planted)
        assert res_mat.n_emitted == res_list.n_emitted

    def test_fingerprint_matches_across_forms(self):
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        sub = {b"a": [b"4"]}
        words = [b"banana"]
        digs = sorted(DIGS[:37], reverse=True)  # unsorted on purpose
        mat = np.frombuffer(b"".join(digs), np.uint8).reshape(-1, 16)
        spec = AttackSpec(mode="default", algo="md5")
        cfg = SweepConfig(lanes=32, num_blocks=8)
        s1 = Sweep(spec, sub, words, digs, config=cfg)
        s2 = Sweep(spec, sub, words, mat, config=cfg)
        assert s1.fingerprint == s2.fingerprint


def test_cr_separated_file_errors_like_old_reader(tmp_path):
    """A CR-separated (classic Mac) file is ONE long line to the \n-split
    reader — it must error, not silently parse (review regression)."""
    body = DIGS[0].hex().encode() + b"\r" + DIGS[1].hex().encode() + b"\r"
    p = tmp_path / "left.txt"
    p.write_bytes(body)
    with pytest.raises(SystemExit):
        _read_digests(str(p), "md5")


def test_host_digest_lookup_forms():
    from hashcat_a5_table_generator_tpu.ops.membership import (
        HostDigestLookup,
    )

    digs = DIGS[:50]
    mat = np.frombuffer(b"".join(digs), np.uint8).reshape(-1, 16)
    for lk in (HostDigestLookup(digs), HostDigestLookup(mat)):
        assert len(lk) == 50
        assert digs[7] in lk
        assert hashlib.md5(b"nope").digest() not in lk
        assert b"short" not in lk
    assert (HostDigestLookup(digs).sorted_blob()
            == HostDigestLookup(mat).sorted_blob()
            == b"".join(sorted(digs)))
    empty = HostDigestLookup(np.zeros((0, 16), np.uint8))
    assert len(empty) == 0 and DIGS[0] not in empty
    assert empty.sorted_blob() == b""

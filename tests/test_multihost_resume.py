"""Pod-scale checkpoint/resume: a 2-process BUCKETED crack sweep runs to
completion, then the SAME pod relaunches with the same per-host checkpoint
paths — every process must report resumed=True, replay its checkpointed
hits, and the combined hit set must equal the fresh run's (SURVEY.md §5
failure detection/recovery at the multihost level: pod recovery =
relaunch, each host resumes its own stripe manifest)."""

import hashlib
import json
import os
import pathlib
import socket
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

_CHILD = r"""
import json, os, sys

pid = int(sys.argv[1])
port = sys.argv[2]
outdir = sys.argv[3]
ck = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)

import jax

jax.config.update("jax_platforms", "cpu")

from hashcat_a5_table_generator_tpu.parallel import multihost

multihost.initialize(f"127.0.0.1:{port}", 2, pid)

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.ops.packing import bucket_words
from hashcat_a5_table_generator_tpu.parallel.multihost import (
    run_crack_multihost,
)
from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"extraordinarily", b"sass"]
digests = [bytes.fromhex(h) for h in json.loads(sys.argv[5])]

spec = AttackSpec(mode="default", algo="md5")
res = run_crack_multihost(
    spec, LEET, bucket_words(WORDS, buckets=(8, 16)), digests,
    # packed_blocks=False forces the fixed-stride (accelerator) layout so
    # the pod-resume path keeps stride coverage on the CPU test backend.
    config=SweepConfig(lanes=64, num_blocks=16, checkpoint_path=ck,
                       packed_blocks=False),
)
with open(os.path.join(outdir, f"res{pid}.json"), "w") as fh:
    json.dump({
        "resumed": res.resumed,
        "n_hits": res.n_hits,
        "hits": [[h.word_index, h.variant_rank, h.candidate.hex()]
                 for h in res.hits],
    }, fh)
"""


def _launch_pod(tmp_path, ck, digest_arg, tag):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "child_resume.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    outdir = tmp_path / tag
    outdir.mkdir()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(p), str(port), str(outdir),
             str(ck), digest_arg],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]
    return [json.load(open(outdir / f"res{p}.json")) for p in range(2)]


def test_pod_relaunch_resumes_bucketed_checkpoints(tmp_path,
                                                   pod_collectives):
    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates

    leet = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
    words = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
             b"oboe", b"extraordinarily", b"sass"]
    oracle = []
    for w in words:
        oracle.extend(iter_candidates(w, leet, 0, 15))
    planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
    digest_arg = json.dumps([hashlib.md5(c).digest().hex() for c in planted])

    ck = tmp_path / "pod.ck"
    first = _launch_pod(tmp_path, ck, digest_arg, "first")
    assert first[0] == first[1]
    assert first[0]["resumed"] is False
    assert first[0]["n_hits"] == len(planted)
    # Per-host bucket manifests exist (FILE.pN + per-bucket .wW cursors).
    assert (tmp_path / "pod.ck.p0").exists()
    assert (tmp_path / "pod.ck.p1").exists()

    second = _launch_pod(tmp_path, ck, digest_arg, "second")
    assert second[0] == second[1]
    assert second[0]["resumed"] is True
    assert second[0]["hits"] == first[0]["hits"]

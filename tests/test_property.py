"""Hypothesis property tests (SURVEY.md §4.4): random tables × words vs the
oracle — keyspace counts, mode quirks (Q1/Q2), parser/emitter round-trips,
and the central enumeration theorem: the device plans' mixed-radix
index-decode (``decode_variant`` over every rank) reproduces the recursive
DFS engines' multiset exactly, for every mode, without touching a device.
"""

from collections import Counter

import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    build_plan,
    decode_variant,
)
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.oracle.keyspace import count_candidates
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import Layout
from hashcat_a5_table_generator_tpu.tables.parser import parse_substitution_table
from hashcat_a5_table_generator_tpu.utils.hexenc import hex_notation_encode
from hashcat_a5_table_generator_tpu.tables.parser import decode_hex_notation

# Small alphabet so keys overlap and multi-char keys collide with
# single-char ones (the hard enumeration cases).
ALPHA = b"abc"

def _bytes_from(alphabet: bytes, min_size: int, max_size: int):
    return st.lists(
        st.sampled_from(list(alphabet)), min_size=min_size, max_size=max_size
    ).map(bytes)


keys = _bytes_from(ALPHA, 1, 2)
# Values may lengthen, shorten (empty allowed: "a=" is a legal table line)
# or contain other keys (cascade-hazard food for suball fallback analysis).
values = _bytes_from(ALPHA + b"XY", 0, 3)
tables = st.dictionaries(
    keys, st.lists(values, min_size=1, max_size=2), min_size=1, max_size=4
)
words = _bytes_from(ALPHA, 0, 6)
windows = st.tuples(st.integers(0, 3), st.integers(0, 6)).filter(
    lambda t: t[0] <= t[1]
)

MODES = [
    dict(substitute_all=False, reverse=False),
    dict(substitute_all=False, reverse=True),
    dict(substitute_all=True, reverse=False),
    dict(substitute_all=True, reverse=True),
]
MODE_NAME = ["default", "reverse", "suball", "suball-reverse"]


def oracle(word, table, mn, mx, **mode):
    return list(
        iter_candidates(word, table, mn, mx, bug_compat=False, **mode)
    )


@settings(max_examples=120, deadline=None)
@given(word=words, table=tables, window=windows)
@pytest.mark.parametrize("mode_i", range(4))
def test_keyspace_count_exact(mode_i, word, table, window):
    mn, mx = window
    mode = MODES[mode_i]
    assert count_candidates(word, table, mn, mx, **mode) == len(
        oracle(word, table, mn, mx, **mode)
    )


@settings(max_examples=100, deadline=None)
@given(word=words, table=tables)
def test_q1_original_emission(word, table):
    # Q1: at min=0, default mode never emits the unmodified word (min is
    # silently bumped to 1); the other three always emit it (k=0 combo /
    # empty choice / empty subset). For the default-mode half, restrict to
    # length-preserving non-identity tables: with length CHANGES a pair of
    # substitutions can reconstruct the original (hypothesis found
    # word=b'aa', {a: ['', 'aa']} -> '' + 'aa' == original).
    if all(
        v != k and len(v) == len(k) for k, vs in table.items() for v in vs
    ):
        d = oracle(word, table, 0, 15, substitute_all=False, reverse=False)
        assert word not in d
    for mode in MODES[1:]:
        out = oracle(word, table, 0, 15, **mode)
        assert out.count(word) >= 1


@settings(max_examples=100, deadline=None)
@given(word=words, table=tables, window=windows)
def test_q2_reverse_uses_first_option_only(word, table, window):
    mn, mx = window
    first_only = {k: v[:1] for k, v in table.items()}
    got = oracle(word, table, mn, mx, substitute_all=False, reverse=True)
    want = oracle(word, first_only, mn, mx, substitute_all=False, reverse=True)
    assert got == want


@settings(max_examples=60, deadline=None)
@given(word=words, table=tables, window=windows)
@pytest.mark.parametrize("mode_i", range(4))
def test_index_decode_equals_dfs_multiset(mode_i, word, table, window):
    """The enumeration theorem (SURVEY.md §7 hard part b): decoding EVERY
    rank of the device plan's mixed-radix space — dropping count-window
    misses and overlap clashes — yields exactly the DFS engines' multiset."""
    mn, mx = window
    mode = MODES[mode_i]
    spec = AttackSpec(
        mode=MODE_NAME[mode_i], min_substitute=mn, max_substitute=mx
    )
    ct = compile_table(table)
    plan = build_plan(spec, ct, pack_words([word]))
    if plan.fallback[0]:
        # Oracle-routed by design: overlaps, empty keys, or genuinely
        # pathological cascades. Closable containment hazards stay on the
        # decode path (suball cascade closure) and ARE checked here.
        return
    total = plan.n_variants[0]
    if total > 4096:
        return  # keep the exhaustive decode bounded
    got = Counter()
    for rank in range(total):
        try:
            got[decode_variant(plan, ct, spec, 0, rank)] += 1
        except ValueError:
            pass  # masked lane: window miss or overlap clash
    want = Counter(oracle(word, table, mn, mx, **mode))
    assert got == want


@settings(max_examples=40, deadline=None)
@given(word=words, table=tables,
       window=st.tuples(st.integers(1, 2), st.integers(1, 4)).filter(
           lambda t: t[0] <= t[1]))
def test_windowed_unrank_equals_masked_full(word, table, window):
    """Count-windowed enumeration theorem: unranking the windowed plan's
    [0, T) visits exactly the in-window, non-clashing variants the full
    mixed-radix plan yields after masking — same multiset, fewer ranks."""
    from hashcat_a5_table_generator_tpu.ops.expand_matches import (
        build_match_plan,
    )

    mn, mx = window
    spec = AttackSpec(mode="default", min_substitute=mn, max_substitute=mx)
    ct = compile_table(table)
    packed = pack_words([word])
    full = build_match_plan(ct, packed)
    win = build_match_plan(
        ct, packed, min_substitute=spec.effective_min, max_substitute=mx
    )
    if full.n_variants[0] > 4096:
        return  # keep the exhaustive decode bounded
    if win.windowed:
        assert win.n_variants[0] <= full.n_variants[0]

    def multiset(plan):
        got = Counter()
        for rank in range(plan.n_variants[0]):
            try:
                got[decode_variant(plan, ct, spec, 0, rank)] += 1
            except ValueError:
                pass  # masked: window miss or overlap clash
        return got

    assert multiset(win) == multiset(full)


@settings(max_examples=100, deadline=None)
@given(
    pairs=st.lists(
        st.tuples(
            st.text(min_size=0, max_size=3),
            st.text(min_size=0, max_size=3),
        ),
        min_size=0,
        max_size=6,
    )
)
def test_layout_emit_parse_round_trip(pairs):
    # Emitter escaping must survive a re-parse for ANY printable pairs —
    # including '=', '#', whitespace and empty strings (empty keys emit as
    # '=v' and parse back to the inert empty key, matching the reference).
    layout = Layout("prop", tuple(pairs))
    text = layout.to_table_bytes()
    reparsed = parse_substitution_table(text)
    want = {}
    for k, v in pairs:
        kb, vb = k.encode(), v.encode()
        # The parser's TrimSpace drops lines whose whole content trims away;
        # the emitter hex-escapes those, so nothing is ever lost — except
        # pure-comment keys which are escaped too. Model the contract:
        want.setdefault(kb, []).append(vb)
    assert reparsed == {k: v for k, v in want.items()}


@settings(max_examples=200, deadline=None)
@given(data=st.binary(min_size=1, max_size=32))
def test_hex_notation_round_trip(data):
    # Non-empty only: "$HEX[]" is 6 bytes and the reference's decoder
    # passes anything under 7 bytes through verbatim (len<7 rule), so the
    # empty payload cannot round-trip — and is never emitted (an empty
    # candidate never needs_hex_notation).
    assert decode_hex_notation(hex_notation_encode(data)) == data


@settings(max_examples=60, deadline=None)
@given(word=words, table=tables)
def test_multiplicity_q7_duplicate_values_double(word, table):
    # Duplicating every option list doubles the multiplicity of every
    # substituted candidate (no dedupe anywhere — Q7).
    doubled = {k: v + v for k, v in table.items()}
    base = Counter(oracle(word, table, 1, 15, substitute_all=False,
                          reverse=False))
    got = Counter(oracle(word, doubled, 1, 15, substitute_all=False,
                         reverse=False))
    # Each k-substitution variant contributes 2^k >= 2 copies after
    # doubling; a candidate STRING may aggregate variants of different k
    # (hypothesis: word=b'aa', {a: [a]} gives 3 -> 8, not a multiple), so
    # the per-candidate law is support equality + at-least-doubling.
    assert set(got) == set(base)
    for cand, n in base.items():
        assert got[cand] >= 2 * n


word_lists = st.lists(words, min_size=1, max_size=6)


@settings(max_examples=100, deadline=None)
@given(table=tables, wl=word_lists, first=st.booleans())
def test_vectorized_match_builder_equals_scalar(table, wl, first):
    """build_match_plan's batch scan vs the per-word find_matches loop:
    every slot field and variant total identical (the vectorized path
    replaced the loop silently, so any divergence is stream corruption)."""
    from hashcat_a5_table_generator_tpu.ops.expand_matches import (
        build_match_plan, find_matches,
    )

    ct = compile_table(table)
    packed = pack_words(wl)
    plan = build_match_plan(ct, packed, first_option_only=first)
    for i in range(packed.batch):
        matches = find_matches(packed.word(i), ct)
        total = 1
        for s, (pos, klen, ki) in enumerate(matches):
            vc = int(ct.val_count[ki])
            radix = 1 if vc == 0 else (2 if first else vc + 1)
            assert plan.match_pos[i, s] == pos
            assert plan.match_len[i, s] == klen
            assert plan.match_radix[i, s] == radix
            assert plan.match_val_start[i, s] == ct.val_start[ki]
            total *= radix
        assert all(
            plan.match_radix[i, s] == 1
            for s in range(len(matches), plan.num_slots)
        )
        assert plan.n_variants[i] == total


@settings(max_examples=100, deadline=None)
@given(table=tables, wl=word_lists, first=st.booleans())
def test_vectorized_suball_builder_equals_scalar(table, wl, first):
    """The vectorized suball builder vs the scalar segment builder under
    the documented contract (tests.test_expand_suball.
    assert_fast_plan_equiv): random tables include multi-char keys,
    overlapping occurrences, and cascade hazards — fallback flags must
    agree exactly and live-row fields must be identical."""
    import hashcat_a5_table_generator_tpu.ops.expand_suball as es
    from tests.test_expand_suball import assert_fast_plan_equiv

    ct = compile_table(table)
    packed = pack_words(wl)
    fast = es.build_suball_plan(ct, packed, first_option_only=first)
    orig = es._build_suball_plan_fast
    try:
        es._build_suball_plan_fast = lambda *a, **k: None
        slow = es.build_suball_plan(ct, packed, first_option_only=first)
    finally:
        es._build_suball_plan_fast = orig
    assert_fast_plan_equiv(fast, slow)

"""Tests for the table compiler (tables/compile.py) and word packing
(ops/packing.py)."""

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.ops.packing import (
    bucket_words,
    pack_words,
    read_wordlist,
)
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import BUILTIN_LAYOUTS
from hashcat_a5_table_generator_tpu.tables.parser import read_substitution_table


def test_compile_roundtrip_simple():
    sub_map = {b"a": [b"X", b"YY"], b"ss": [b"\xc3\x9f"], b"b": [b"Z"]}
    ct = compile_table(sub_map)
    assert ct.keys == (b"a", b"b", b"ss")
    assert ct.num_keys == 3
    assert ct.num_values == 4
    assert ct.max_key_len == 2
    assert ct.max_val_len == 2
    # Values preserve per-key order and multiplicity.
    assert ct.values_of(ct.key_index(b"a")) == [b"X", b"YY"]
    assert ct.values_of(ct.key_index(b"ss")) == [b"\xc3\x9f"]
    # Single-byte LUT hits 'a' and 'b', not 'ss'.
    assert ct.byte_to_key[ord("a")] == ct.key_index(b"a")
    assert ct.byte_to_key[ord("b")] == ct.key_index(b"b")
    assert ct.byte_to_key[ord("s")] == -1
    assert not ct.all_keys_single_byte


def test_compile_duplicate_values_kept():
    ct = compile_table({b"a": [b"X", b"X"]})  # Q7: multiplicity is parity
    assert ct.values_of(0) == [b"X", b"X"]


def test_compile_cascade_hazard_detection():
    # 'b' sorts after 'a' and appears in a's value -> the sorted ReplaceAll
    # cascade would re-substitute the inserted 'b'.
    ct = compile_table({b"a": [b"b"], b"b": [b"c"]})
    assert not ct.cascade_free
    assert ct.cascade_hazard[ct.key_index(b"a"), ct.key_index(b"b")]
    # The reverse direction is safe: 'a' is applied before 'b' inserts it.
    assert compile_table({b"b": [b"a"], b"a": [b"x"]}).cascade_free
    assert compile_table({b"a": [b"\xd0\x90"]}).cascade_free
    # Self-insertion is safe too (a pattern never re-matches its own pass).
    assert compile_table({b"a": [b"aa"]}).cascade_free
    # An empty key sorts first, so it can never re-match later-inserted text;
    # such tables are excluded from fast paths via has_empty_key instead.
    assert compile_table({b"": [b"z"], b"a": [b"xy"]}).cascade_free
    assert compile_table({b"": [b"z"], b"a": [b"xy"]}).has_empty_key


def test_compile_cascade_crossing_classification():
    from hashcat_a5_table_generator_tpu.tables.compile import (
        boundary_match_possible,
    )

    # Containment-only hazard: flagged hazardous but NOT crossing — the
    # closure planner may rewrite it on device.
    ct = compile_table({b"a": [b"b"], b"b": [b"c"]})
    i, j = ct.key_index(b"a"), ct.key_index(b"b")
    assert ct.cascade_hazard[i, j] and not ct.cascade_crossing[i, j]
    # Boundary crossing (case c: 'cb' starts with the suffix of value 'c').
    ct = compile_table({b"a": [b"c"], b"cb": [b"Z"]})
    i, j = ct.key_index(b"a"), ct.key_index(b"cb")
    assert ct.cascade_hazard[i, j] and ct.cascade_crossing[i, j]
    # Empty value (case d: splice join) is a crossing hazard.
    ct = compile_table({b"a": [b""], b"bc": [b"Z"]})
    i, j = ct.key_index(b"a"), ct.key_index(b"bc")
    assert ct.cascade_crossing[i, j]
    # The predicate itself: containment is deliberately not "crossing".
    assert not boundary_match_possible(b"bb", b"b")
    assert boundary_match_possible(b"c", b"cb")  # left overhang
    assert boundary_match_possible(b"c", b"bc")  # right overhang
    assert boundary_match_possible(b"", b"x")  # splice join
    # qwerty-azerty: every hazard pair is containment-only — the whole
    # table closes on device (PERF.md §14).
    az = compile_table(BUILTIN_LAYOUTS["qwerty-azerty"].to_substitution_map())
    assert az.cascade_hazard.any() and not az.cascade_crossing.any()


def test_compile_empty_key_and_empty_map():
    ct = compile_table({b"": [b"x"]})
    assert ct.has_empty_key and not ct.all_keys_single_byte
    empty = compile_table({})
    assert empty.num_keys == 0 and empty.key_bytes.shape == (0, 1)
    # Value arrays keep one zero row (device kernels gather value rows by
    # index; a 0-row axis makes even a never-selected gather go OOB).
    assert empty.val_bytes.shape == (1, 1) and empty.val_len.shape == (1,)
    assert empty.val_count.sum() == 0


@pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
def test_compile_builtin_layouts(name):
    sub_map = BUILTIN_LAYOUTS[name].to_substitution_map()
    ct = compile_table(sub_map)
    assert ct.num_keys == len(sub_map)
    for key, vals in sub_map.items():
        assert ct.values_of(ct.key_index(key)) == list(vals)
    # Every monodirectional transliteration table is cascade-free; the
    # bidirectional qwerty-azerty merges both directions, so a later-sorted
    # key can occur in an earlier key's value (e.g. '!' -> '8' with '8' a key).
    assert ct.cascade_free == (name != "qwerty-azerty")


def test_compile_upstream_tables_hazards(upstream_reference):
    for table in sorted(upstream_reference.glob("*.table")):
        ct = compile_table(read_substitution_table(str(table)))
        assert ct.cascade_free == (table.stem != "qwerty-azerty"), table.name


def test_pack_words_basic():
    pw = pack_words([b"abc", b"", b"0123456789"])
    assert pw.width == 12  # multiple of 4 covering the longest
    assert pw.words() == [b"abc", b"", b"0123456789"]
    assert list(pw.lengths) == [3, 0, 10]
    assert list(pw.index) == [0, 1, 2]
    assert pw.tokens.dtype == np.uint8


def test_pack_words_width_overflow():
    with pytest.raises(ValueError):
        pack_words([b"abcdef"], width=4)


def test_bucket_words():
    words = [b"a" * n for n in (3, 17, 70, 5, 200)]
    buckets = bucket_words(words)
    assert sorted(buckets) == [16, 32, 128, 256]
    assert buckets[16].words() == [b"aaa", b"aaaaa"]
    assert list(buckets[16].index) == [0, 3]
    assert list(buckets[128].index) == [2]
    assert list(buckets[256].index) == [4]


def test_bucket_words_q8_guard():
    with pytest.raises(ValueError, match="Q8"):
        bucket_words([b"x" * (64 * 1024 + 1)])


def test_read_wordlist(tmp_path):
    p = tmp_path / "dict.txt"
    p.write_bytes(b"alpha\r\nbeta\n\ngamma")
    assert read_wordlist(str(p)) == [b"alpha", b"beta", b"", b"gamma"]
    p.write_bytes(b"one\ntwo\n")
    assert read_wordlist(str(p)) == [b"one", b"two"]
    p.write_bytes(b"")
    assert read_wordlist(str(p)) == []

"""Cascade-closure parity suite (PERF.md §14).

The substitute-all planner closes containment-only ReplaceAll hazards on
device (``ops.expand_suball``): hazard slots get joint value tables holding
the statically pre-cascaded rewrites. These tests pin the whole contract:

* the qwerty-azerty table — the reference's headline bidirectional config
  and the one shipped table with hazards — runs END-TO-END with the device
  stream word-multiset-identical to the CPU oracle, and its fallback share
  drops below 1% on a rockyou-class wordlist (the acceptance number);
* randomized synthetic hazard tables (seeded fuzz — the hypothesis-driven
  twin lives in test_property.py) keep multiset parity for every
  non-fallback word, closure on or off;
* the Q4 canonicalized sorted-pattern cascade ORDER is what closure bakes
  into its joint tables (order vectors with order-sensitive rewrites);
* the three-way routing stats (device-clean / device-closed /
  oracle-fallback) are reported by the sweep — the instrument the
  acceptance criterion reads.
"""

import io
import json
import random
from collections import Counter

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    build_plan,
    decode_variant,
)
from hashcat_a5_table_generator_tpu.oracle.engines import (
    iter_candidates,
    process_word_substitute_all,
)
from hashcat_a5_table_generator_tpu.ops.expand_suball import (
    MAX_CLOSE_OPTS,
    _close_pattern_set,
    build_suball_plan,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.runtime.progress import ProgressReporter
from hashcat_a5_table_generator_tpu.runtime.sinks import CandidateWriter
from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import BUILTIN_LAYOUTS

from test_expand_suball import assert_parity, run_device_suball

AZERTY = BUILTIN_LAYOUTS["qwerty-azerty"].to_substitution_map()


def _rockyou_like(n: int, seed: int = 0):
    """The bench's deterministic rockyou-class generator (lowercase stems +
    digit tails) — the population PERF.md §5's 10.2% was measured on."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    try:
        from bench import synth_wordlist
    finally:
        sys.path.pop(0)
    return synth_wordlist(n, seed)


class TestAzertyEndToEnd:
    def test_hazard_words_close_with_parity(self):
        # Every hazard pair class the table has: a+q, w+z, m+",", case
        # pairs, plus clean and empty words.
        words = [b"aqua", b"wizard", b"ma,am", b"qa", b"zw", b"AQ",
                 b"password", b"", b"a", b"Pa,ss", b"jazzqa"]
        fallbacks = assert_parity(AZERTY, words)
        assert not fallbacks  # all azerty hazards here are closable
        ct = compile_table(AZERTY)
        plan = build_suball_plan(ct, pack_words(words))
        assert plan.closed is not None
        for i, w in enumerate(words):
            has_aq = b"a" in w and b"q" in w
            has_wz = b"w" in w and b"z" in w
            has_mc = b"m" in w and b"," in w
            expect = has_aq or has_wz or has_mc or (b"A" in w and b"Q" in w)
            assert bool(plan.closed[i]) == expect, w

    def test_fallback_share_below_one_percent(self):
        # The acceptance number: PERF.md §5 measured 10.2% of words
        # falling back pre-closure; closure must push it under 1%.
        words = _rockyou_like(5000)
        sweep = Sweep(AttackSpec(mode="suball", algo="md5"), AZERTY, words,
                      config=SweepConfig(lanes=1 << 12, num_blocks=32))
        r = sweep.routing
        assert r["device_clean"] + r["device_closed"] + \
            r["oracle_fallback"] == 5000
        assert r["device_closed"] > 0  # hazard words exist and closed
        assert r["oracle_fallback"] / 5000 < 0.01

    def test_sweep_stream_matches_oracle(self):
        # End-to-end candidates mode over hazard-heavy words: global
        # word-order with per-word multiset parity, closure active.
        words = [b"zaq", b"aqua", b"xyz", b"wz,m", b"maze"]
        spec = AttackSpec(mode="suball", algo="md5")
        sweep = Sweep(spec, AZERTY, words,
                      config=SweepConfig(lanes=256, num_blocks=16))
        assert not sweep.fallback_rows  # everything closed or clean
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            res = sweep.run_candidates(w)
        got = buf.getvalue().splitlines()
        pos = 0
        for word in words:
            seg = list(iter_candidates(word, AZERTY, 0, 15,
                                       substitute_all=True))
            assert Counter(got[pos:pos + len(seg)]) == Counter(seg), word
            pos += len(seg)
        assert pos == len(got) == res.n_emitted
        assert res.routing["device_closed"] >= 3


class TestQ4OrderVectors:
    """Closure bakes the Q4 sorted-pattern ReplaceAll order into its joint
    tables; these vectors have order-SENSITIVE rewrites, so any deviation
    from the canonical order changes bytes."""

    def test_two_stage_chain_order(self):
        # 'a'->'b' then 'b'->'c': with both chosen the span must cascade
        # a -> b -> c (sorted order), never stop at 'b'.
        got, fallbacks = run_device_suball(
            {b"a": [b"b"], b"b": [b"c"]}, [b"ab"], 0, 15
        )
        assert not fallbacks
        assert got[0] == Counter({b"ab": 1, b"bb": 1, b"ac": 1, b"cc": 1})

    def test_three_stage_chain_order(self):
        got, fallbacks = run_device_suball(
            {b"a": [b"b"], b"b": [b"c"], b"c": [b"d"]}, [b"abc"], 0, 15
        )
        assert not fallbacks
        # Full choice: a->b->c->d everywhere (strictly sorted cascade).
        assert got[0][b"ddd"] == 1
        # b,c chosen without a: 'abc' -> 'acc' -> 'add'... order pins it.
        want = Counter(process_word_substitute_all(
            b"abc", {b"a": [b"b"], b"b": [b"c"], b"c": [b"d"]}, 0, 15
        ))
        assert got[0] == want

    def test_multiplicity_of_rewritten_values(self):
        # Q7 under closure: duplicate JOINT rows must keep multiplicity
        # ('a'->'bb' with 'b'->'c' gives 'cc'; distinct digit combos that
        # collide byte-wise stay distinct candidates).
        sub = {b"a": [b"bb"], b"b": [b"c"]}
        got, fallbacks = run_device_suball(sub, [b"ab"], 0, 15)
        assert not fallbacks
        assert got[0] == Counter(process_word_substitute_all(
            b"ab", sub, 0, 15
        ))


class TestSyntheticFuzz:
    """Seeded random hazard tables × words: device multiset == oracle for
    every non-fallback word, and closure never changes WHAT is emitted —
    only where it's computed. (The hypothesis twin in test_property.py
    drives the same invariant through decode_variant when hypothesis is
    installed; this one always runs.)"""

    ALPHA = b"abc"

    def _random_table(self, rng):
        table = {}
        for _ in range(rng.randint(1, 4)):
            klen = rng.randint(1, 2)
            key = bytes(rng.choice(self.ALPHA) for _ in range(klen))
            vals = []
            for _ in range(rng.randint(1, 2)):
                vlen = rng.randint(0, 3)
                vals.append(bytes(
                    rng.choice(self.ALPHA + b"XY") for _ in range(vlen)
                ))
            table.setdefault(key, []).extend(vals)
        return table

    def _random_words(self, rng):
        return [
            bytes(rng.choice(self.ALPHA) for _ in range(rng.randint(0, 6)))
            for _ in range(rng.randint(1, 4))
        ]

    @pytest.mark.parametrize("seed", range(40))
    def test_fuzz_parity(self, seed):
        rng = random.Random(seed)
        table = self._random_table(rng)
        words = self._random_words(rng)
        mn = rng.randint(0, 2)
        mx = rng.randint(mn, 6)
        fallbacks = assert_parity(table, words, mn, mx)
        # Closure must only ever SHRINK the fallback set vs closure-off.
        ct = compile_table(table)
        import hashcat_a5_table_generator_tpu.ops.expand_suball as es

        plan_on = build_suball_plan(ct, pack_words(words))
        import os

        os.environ["A5GEN_CASCADE_CLOSE"] = "off"
        try:
            plan_off = build_suball_plan(ct, pack_words(words))
        finally:
            del os.environ["A5GEN_CASCADE_CLOSE"]
        assert set(np.nonzero(plan_on.fallback)[0]) <= set(
            np.nonzero(plan_off.fallback)[0]
        )
        assert es.close_enabled()
        assert fallbacks == set(np.nonzero(plan_on.fallback)[0])

    @pytest.mark.parametrize("seed", range(20))
    def test_fuzz_decode_variant(self, seed):
        # Host-side decode over every rank equals the oracle multiset for
        # non-fallback words (the enumeration-theorem invariant, closure
        # included).
        rng = random.Random(1000 + seed)
        table = self._random_table(rng)
        words = self._random_words(rng)
        spec = AttackSpec(mode="suball", algo="md5")
        ct = compile_table(table)
        plan = build_plan(spec, ct, pack_words(words))
        for i, word in enumerate(words):
            if plan.fallback[i] or plan.n_variants[i] > 4096:
                continue
            got = Counter()
            for rank in range(plan.n_variants[i]):
                try:
                    got[decode_variant(plan, ct, spec, i, rank)] += 1
                except ValueError:
                    pass
            want = Counter(process_word_substitute_all(
                word, table, spec.effective_min, spec.max_substitute
            ))
            assert got == want, (word, table)


class TestClosureAnalysis:
    def test_crossing_value_rejected(self):
        ct = compile_table({b"a": [b"c"], b"cb": [b"Z"]})
        kis = tuple(range(ct.num_keys))
        assert _close_pattern_set(ct, kis, False) is None

    def test_empty_value_splice_rejected(self):
        # b'' inserted value joins context: any later pattern could match
        # across the splice — pathological.
        ct = compile_table({b"a": [b""], b"bc": [b"Z"]})
        assert _close_pattern_set(ct, (0, 1), False) is None

    def test_cap_overflow_falls_back(self):
        # Joint combos past MAX_CLOSE_OPTS stay on the oracle.
        sub = {b",": [b";", b"m", b"M"], b"m": [b",", b";"],
               b";": [b"m", b",", b"M"], b"M": [b";", b","]}
        ct = compile_table(sub)
        plan = build_suball_plan(ct, pack_words([b"m,", b"mM,"]))
        assert plan.closed is not None and bool(plan.closed[0])
        assert bool(plan.fallback[1])  # 3*4*3 = 36 > MAX_CLOSE_OPTS
        assert MAX_CLOSE_OPTS == 12
        assert_parity(sub, [b"m,", b"mM,"])

    def test_clamped_away_hazard_is_clean_not_closed(self):
        # suball-reverse clamps to subs[0]; a hazard living only in the
        # clamped-away option never manifests, so the word must be CLEAN
        # (span-splice path, scalar-units still eligible) — neither closed
        # (which would crash the K=1 fused path) nor fallback.
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        sub = {b"a": [b"X", b"b"], b"b": [b"c"]}
        spec = AttackSpec(mode="suball-reverse", algo="md5")
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words([b"ab"]))
        assert not plan.fallback[0]
        assert plan.closed is None and plan.close_next is None
        assert scalar_units_for(plan)  # K=1 fast path stays open
        got = Counter()
        for rank in range(plan.n_variants[0]):
            try:
                got[decode_variant(plan, ct, spec, 0, rank)] += 1
            except ValueError:
                pass
        want = Counter(iter_candidates(
            b"ab", sub, 0, 15, substitute_all=True, reverse=True
        ))
        assert got == want

    def test_first_option_only_closure(self):
        # suball-reverse clamps to subs[0]; the joint tables must use the
        # clamped option sets.
        sub = {b"a": [b"b", b"x"], b"b": [b"c", b"d"]}
        words = [b"ab", b"ba"]
        spec = AttackSpec(mode="suball-reverse", algo="md5")
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(words))
        assert plan.closed is not None and plan.closed.all()
        for i, word in enumerate(words):
            got = Counter()
            for rank in range(plan.n_variants[i]):
                try:
                    got[decode_variant(plan, ct, spec, i, rank)] += 1
                except ValueError:
                    pass
            want = Counter(iter_candidates(
                word, sub, 0, 15, substitute_all=True, reverse=True
            ))
            assert got == want, word


def test_raw_option_cap_unchanged_by_closure_widening():
    # _MAX_OPTIONS grew 8 -> 12 to admit joint closure tables; a PLAIN
    # table with 9+ options per key must still be rejected (the compile
    # -time soft cap the old bound enforced).
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        opts_for_config,
    )

    sub = {b"a": [b"%d" % i for i in range(9)]}
    spec = AttackSpec(mode="suball", algo="md5")
    ct = compile_table(sub)
    plan = build_plan(spec, ct, pack_words([b"aa"]))
    assert opts_for_config(spec, plan, ct, block_stride=128,
                           num_blocks=16, require_tpu=False) is None
    # A closed azerty plan (joint width 9 > 8) stays eligible.
    ct_az = compile_table(AZERTY)
    plan_az = build_plan(spec, ct_az, pack_words([b"ma,am"]))
    assert plan_az.close_opts == 9
    assert opts_for_config(spec, plan_az, ct_az, block_stride=128,
                           num_blocks=16, require_tpu=False) == 9


class TestRoutingStats:
    def test_azerty_classification_pinned(self):
        # The instrument the acceptance criterion reads: exact three-way
        # split for a handful of words whose classes are known.
        words = [
            b"password",  # 'a' present, no partner -> clean
            b"aqua",      # a+q hazard -> closed
            b"wizard",    # w+z hazard -> closed
            b"xyxy",      # no patterns at all -> clean
            b"m,;",       # , + ; + m joint table overflow -> oracle
        ]
        sweep = Sweep(AttackSpec(mode="suball", algo="md5"), AZERTY, words,
                      config=SweepConfig(lanes=256, num_blocks=16))
        assert sweep.routing == {
            "device_clean": 2,
            "device_closed": 2,
            "oracle_fallback": 1,
        }

    def test_routing_in_progress_json_and_result(self):
        words = [b"aqua", b"xyxy", b"m,;"]
        stream = io.StringIO()
        progress = ProgressReporter(len(words), every_s=0.0, stream=stream)
        spec = AttackSpec(mode="suball", algo="md5")
        sweep = Sweep(spec, AZERTY, words,
                      config=SweepConfig(lanes=256, num_blocks=16,
                                         progress=progress))
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            res = sweep.run_candidates(w)
        want = {"device_clean": 1, "device_closed": 1, "oracle_fallback": 1}
        assert res.routing == want
        lines = [json.loads(x) for x in stream.getvalue().splitlines()]
        assert lines and all(
            x["progress"]["routing"] == want for x in lines
        )

    def test_match_mode_routing_all_clean(self):
        sweep = Sweep(AttackSpec(mode="default", algo="md5"),
                      {b"a": [b"4"]}, [b"aa", b"bb"],
                      config=SweepConfig(lanes=256, num_blocks=16))
        assert sweep.routing == {
            "device_clean": 2, "device_closed": 0, "oracle_fallback": 0,
        }

"""Hash-kernel tests: batched jnp MD5/SHA-1/MD4/NTLM vs hashlib ground truth."""

import hashlib

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.ops import hashes
from hashcat_a5_table_generator_tpu.ops.packing import pack_words


def _ref_md4(data: bytes) -> bytes:
    """Pure-python MD4 (hashlib's md4 is an OpenSSL legacy algo, often absent)."""
    try:
        return hashlib.new("md4", data).digest()
    except ValueError:
        pass
    # Minimal reference MD4 used only when OpenSSL lacks the legacy provider.
    import struct

    msg = bytearray(data) + b"\x80"
    while len(msg) % 64 != 56:
        msg += b"\x00"
    msg += struct.pack("<Q", len(data) * 8)
    a, b, c, d = 0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476

    def lrot(x, s):
        x &= 0xFFFFFFFF
        return ((x << s) | (x >> (32 - s))) & 0xFFFFFFFF

    for off in range(0, len(msg), 64):
        x = struct.unpack("<16I", msg[off : off + 64])
        aa, bb, cc, dd = a, b, c, d
        for i in range(16):
            s = (3, 7, 11, 19)[i % 4]
            a = lrot(a + ((b & c) | (~b & d)) + x[i], s)
            a, b, c, d = d, a, b, c
        for i, k in enumerate((0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15)):
            s = (3, 5, 9, 13)[i % 4]
            a = lrot(a + ((b & c) | (b & d) | (c & d)) + x[k] + 0x5A827999, s)
            a, b, c, d = d, a, b, c
        for i, k in enumerate((0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15)):
            s = (3, 9, 11, 15)[i % 4]
            a = lrot(a + (b ^ c ^ d) + x[k] + 0x6ED9EBA1, s)
            a, b, c, d = d, a, b, c
        a = (a + aa) & 0xFFFFFFFF
        b = (b + bb) & 0xFFFFFFFF
        c = (c + cc) & 0xFFFFFFFF
        d = (d + dd) & 0xFFFFFFFF
    return struct.pack("<4I", a, b, c, d)


WORDS = [
    b"",
    b"a",
    b"abc",
    b"password",
    b"hello world",
    bytes(range(33, 88)),  # 55 bytes — largest single-block payload
    bytes(range(0, 56)),  # 56 bytes — forces a second block
    bytes(range(0, 64)),  # exactly one block of data
    b"x" * 119,  # 2-block payload
    b"x" * 120,  # forces a third block
    "пароль".encode("utf-8"),
    "ΠΑΣΣΩΟΡΔ".encode("utf-8"),
]


@pytest.mark.parametrize("algo,ref", [("md5", lambda d: hashlib.md5(d).digest()),
                                      ("sha1", lambda d: hashlib.sha1(d).digest())])
def test_hash_vs_hashlib(algo, ref):
    packed = pack_words(WORDS)
    state = np.asarray(hashes.HASH_FNS[algo](packed.tokens, packed.lengths))
    got = hashes.digest_bytes(state, algo)
    for w, g in zip(WORDS, got):
        assert g == ref(w), (algo, w)


def test_md4_vs_reference():
    packed = pack_words(WORDS)
    got = hashes.digest_bytes(np.asarray(hashes.md4(packed.tokens, packed.lengths)), "md4")
    for w, g in zip(WORDS, got):
        assert g == _ref_md4(w), w


def test_ntlm_known_vectors():
    # Classic NTLM test vectors (MD4 of UTF-16LE password).
    vectors = {
        b"": "31d6cfe0d16ae931b73c59d7e0c089c0",
        b"password": "8846f7eaee8fb117ad06bdd830b7586c",
        b"admin": "209c6174da490caeb422f3fa5a7ae634",
    }
    words = list(vectors)
    packed = pack_words(words)
    got = hashes.digest_bytes(np.asarray(hashes.ntlm(packed.tokens, packed.lengths)), "ntlm")
    for w, g in zip(words, got):
        assert g.hex() == vectors[w], w


def test_ntlm_matches_naive_interleave_for_nonascii():
    # Documented semantics: byte interleave (hashcat default), not UTF-8
    # transcoding — so the reference value is MD4 over bytes+zero bytes.
    w = "пароль".encode("utf-8")
    packed = pack_words([w])
    got = hashes.digest_bytes(np.asarray(hashes.ntlm(packed.tokens, packed.lengths)), "ntlm")[0]
    interleaved = bytes(b for byte in w for b in (byte, 0))
    assert got == _ref_md4(interleaved)


def test_padding_garbage_immunity():
    # Bytes past `length` must not affect the digest.
    base = pack_words([b"secret"], width=64)
    dirty = base.tokens.copy()
    dirty[:, 6:] = 0xAA
    a = hashes.digest_bytes(np.asarray(hashes.md5(base.tokens, base.lengths)), "md5")[0]
    b = hashes.digest_bytes(np.asarray(hashes.md5(dirty, base.lengths)), "md5")[0]
    assert a == b == hashlib.md5(b"secret").digest()


def test_digest_word_roundtrip():
    for algo, ref in (("md5", hashlib.md5), ("sha1", hashlib.sha1)):
        d = ref(b"roundtrip").digest()
        words = hashes.digest_to_words(d, algo)
        assert hashes.digest_bytes(words[None, :], algo)[0] == d
        assert (hashes.digest_to_words(d.hex(), algo) == words).all()


def test_mixed_lengths_one_batch():
    # One compiled program must serve every length in a bucket (static shapes).
    words = [b"a" * n for n in range(0, 56, 7)]
    packed = pack_words(words, width=56)
    state = np.asarray(hashes.jit_md5(packed.tokens, packed.lengths))
    for w, g in zip(words, hashes.digest_bytes(state, "md5")):
        assert g == hashlib.md5(w).digest()

"""Fleet tier (PERF.md §25): router + multi-engine pool.

Fast tier runs IN-PROCESS engine pools (two ``Engine`` instances
behind real ``serve_socket`` unix sockets — full wire realism, no
subprocess jax imports) sharing the suite's 64×16 geometry so the
process step cache serves everything: multi-tenant parity through the
router, pause/resume and migrate with exactly-once redelivery,
crash-replay over a torn socket, the health watchdog, placement, the
checkpoint wire-version gate, and the telemetry engine label.

The REAL multi-process contracts are slow-marked: the kill-one-engine
soak (spawned engines, SIGKILL mid-sweep, byte parity vs solo) and the
affinity compile-reuse instrument (per-process step caches are what
make 1-vs-2 program builds observable).
"""

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import types

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import telemetry
from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
    CheckpointState,
    CheckpointWireIncompatible,
    SweepCursor,
    WIRE_VERSION,
    state_from_doc,
    state_to_doc,
)
from hashcat_a5_table_generator_tpu.runtime.engine import (
    Engine,
    serve_socket,
)
from hashcat_a5_table_generator_tpu.runtime.fleet import (
    FleetError,
    FleetRouter,
    spawn_engines,
)
from hashcat_a5_table_generator_tpu.runtime.fuse import affinity_token
from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig
from tests.test_superstep import LEET, WORDS, oracle_lines

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPEC = AttackSpec(mode="default", algo="md5")

#: Long enough that pause/migrate/crash land mid-sweep at 64 lanes ×
#: superstep=1 (the churn ops are gated on the job's FIRST forwarded
#: hit, which arrives within the first supersteps), short enough for
#: the tier-1 budget.
BIG_WORDS = WORDS * 12


def cfg(**kw):
    return SweepConfig(lanes=64, num_blocks=16, superstep=1, **kw)


def planted_digests(words, picks, decoys=20):
    oracle = oracle_lines(SPEC, LEET, words)
    planted = sorted({oracle[i] for i in picks})
    digs = [hashlib.md5(c).digest() for c in planted]
    digs += [hashlib.md5(b"decoy%d" % i).digest() for i in range(decoys)]
    return digs


def job_doc(jid, words, digests):
    return {
        "op": "submit", "id": jid,
        "words": [w.decode() for w in words],
        "table_map": {"a": ["4", "@"], "o": ["0"], "s": ["$", "5"],
                      "e": ["3"]},
        "digest_list": [d.hex() for d in digests],
        "config": {"lanes": 64, "blocks": 16, "superstep": 1},
    }


def event_hits(events):
    return [
        (e["word_index"], int(e["rank"]), e["plain_hex"], e["digest"])
        for e in events if e.get("event") == "hit"
    ]


def solo_hits(words, digests):
    res = Sweep(SPEC, LEET, words, digests, config=cfg()).run_crack()
    return res, [
        (h.word_index, h.variant_rank, h.candidate.hex(), h.digest_hex)
        for h in res.hits
    ]


# ---------------------------------------------------------------------------
# Checkpoint wire-version discipline
# ---------------------------------------------------------------------------


class TestWireVersion:
    def _state(self):
        return CheckpointState(
            fingerprint="fp", cursor=SweepCursor(3, 10**25),
            n_emitted=7, n_hits=1, hits=[(2, 10**24)], wall_s=0.5,
        )

    def test_doc_carries_wire_version_and_round_trips(self):
        doc = json.loads(json.dumps(state_to_doc(self._state())))
        assert doc["wire_version"] == WIRE_VERSION
        assert state_from_doc(doc) == self._state()

    def test_missing_wire_version_accepted_as_major_1(self):
        # Pre-bump documents (older builds, old on-disk checkpoints)
        # carry no field; the wire format has not changed since.
        doc = state_to_doc(self._state())
        del doc["wire_version"]
        assert state_from_doc(doc) == self._state()

    def test_unknown_major_rejected_typed(self):
        doc = state_to_doc(self._state())
        doc["wire_version"] = "2.0"
        with pytest.raises(CheckpointWireIncompatible) as exc:
            state_from_doc(doc)
        assert "major 2" in str(exc.value)

    def test_minor_drift_accepted(self):
        doc = state_to_doc(self._state())
        doc["wire_version"] = "1.9"
        assert state_from_doc(doc) == self._state()

    def test_garbage_version_rejected_typed(self):
        doc = state_to_doc(self._state())
        doc["wire_version"] = "latest"
        with pytest.raises(CheckpointWireIncompatible):
            state_from_doc(doc)


# ---------------------------------------------------------------------------
# Affinity tokens: engine-side and router-side must agree
# ---------------------------------------------------------------------------


class TestAffinityToken:
    def test_router_doc_token_matches_engine_token(self):
        c = cfg()
        router = FleetRouter(poll_s=0, defaults=c)
        doc = {"algo": "md5", "mode": "default",
               "config": {"lanes": 64, "blocks": 16, "superstep": 1}}
        assert router._doc_token(doc) == affinity_token(SPEC, c)
        router.close(shutdown_engines=False)

    def test_token_distinguishes_static_config(self):
        c = cfg()
        base = affinity_token(SPEC, c)
        assert affinity_token(
            AttackSpec(mode="reverse", algo="md5"), c
        ) != base
        assert affinity_token(SPEC, cfg(pair=0)) != base
        from dataclasses import replace

        assert affinity_token(SPEC, replace(c, lanes=128)) != base


# ---------------------------------------------------------------------------
# Telemetry engine identity (satellite 3)
# ---------------------------------------------------------------------------


class TestEngineLabel:
    def test_snapshot_and_prometheus_carry_engine_label(self):
        telemetry.set_engine_id("e1@host")
        try:
            telemetry.counter("fleettest.label").add(3)
            snap = telemetry.snapshot()
            assert snap["fleettest.label"]["engine"] == "e1@host"
            text = telemetry.to_prometheus(
                {"fleettest.label": snap["fleettest.label"]}
            )
            assert 'a5gen_fleettest_label{engine="e1@host"} ' in text
        finally:
            telemetry.set_engine_id(None)
        # Unlabeled again once cleared.
        assert "engine" not in telemetry.snapshot()["fleettest.label"]

    def test_merge_sums_counters_and_keeps_per_engine_gauges(self):
        a = {
            "jobs": {"type": "counter", "value": 2, "engine": "e1"},
            "fill": {"type": "gauge", "value": 0.25, "agg": "last",
                     "engine": "e1"},
        }
        b = {
            "jobs": {"type": "counter", "value": 3, "engine": "e2"},
            "fill": {"type": "gauge", "value": 0.75, "agg": "last",
                     "engine": "e2"},
        }
        m = telemetry.merge([a, b])
        # Counters sum fleet-wide; the per-member label no longer
        # describes the summed value.
        assert m["jobs"]["value"] == 5
        assert "engine" not in m["jobs"]
        # Conflicting-engine gauges keep per-engine series instead of
        # silently last-one-wins.
        assert "fill" not in m
        assert m['fill{engine="e1"}']["value"] == 0.25
        assert m['fill{engine="e2"}']["value"] == 0.75
        text = telemetry.to_prometheus(m)
        assert 'a5gen_fill{engine="e1"} 0.25' in text
        assert 'a5gen_fill{engine="e2"} 0.75' in text

    def test_merge_same_engine_gauges_still_aggregate(self):
        a = {"g": {"type": "gauge", "value": 1, "agg": "max",
                   "engine": "e1"}}
        b = {"g": {"type": "gauge", "value": 4, "agg": "max",
                   "engine": "e1"}}
        m = telemetry.merge([a, b])
        assert m["g"]["value"] == 4 and m["g"]["engine"] == "e1"


# ---------------------------------------------------------------------------
# Engine stats placement signals (satellite 4)
# ---------------------------------------------------------------------------


class TestStatsPlacementSignals:
    def test_resident_groups_and_counts_surface(self):
        digs = planted_digests(WORDS, (0,))
        eng = Engine(cfg(), auto=False)
        eng.submit(SPEC, LEET, WORDS, digs)
        eng.submit(SPEC, LEET, WORDS, digs)
        eng._admit()
        stats = eng.stats()
        assert stats["jobs_runnable"] == stats["jobs_active"] == 2
        assert stats["jobs_staged"] == 0
        (token,) = stats["resident_groups"]
        assert token == affinity_token(SPEC, cfg())
        eng.run_until_idle()
        stats = eng.stats()
        assert stats["jobs_runnable"] == 0
        assert stats["resident_groups"] == []
        assert "packed_fill" in stats


# ---------------------------------------------------------------------------
# Placement policy (router-level, stub links)
# ---------------------------------------------------------------------------


def _stub_link(engine_id, index, resident=(), load=0,
               health="healthy"):
    return types.SimpleNamespace(
        engine_id=engine_id, index=index, alive=True, draining=False,
        health=health,
        scrape={"resident_groups": list(resident),
                "jobs_runnable": load},
        routed=set(), misses=0,
    )


class TestPlacement:
    def test_affinity_prefers_resident_token(self):
        router = FleetRouter(poll_s=0)
        busy = _stub_link("busy", 0, resident=("tok",), load=9)
        idle = _stub_link("idle", 1, load=0)
        router._links = [busy, idle]
        # Matching token beats the load tie-break...
        assert router._pick("tok") is busy
        # ...and a non-matching job goes to the least-loaded engine.
        assert router._pick("other") is idle

    def test_round_robin_alternates(self):
        router = FleetRouter(place="round-robin", poll_s=0)
        a, b = _stub_link("a", 0), _stub_link("b", 1)
        router._links = [a, b]
        picks = {router._pick("tok").engine_id for _ in range(2)}
        assert picks == {"a", "b"}

    def test_draining_and_dead_excluded(self):
        router = FleetRouter(poll_s=0)
        a, b = _stub_link("a", 0, resident=("tok",)), _stub_link("b", 1)
        a.draining = True
        router._links = [a, b]
        assert router._pick("tok") is b
        b.alive = False
        with pytest.raises(FleetError):
            router._pick("tok")

    def test_submit_with_no_engines_fails_loudly(self):
        router = FleetRouter(poll_s=0)
        with pytest.raises(FleetError):
            router.submit(job_doc("j", WORDS, planted_digests(WORDS,
                                                              (0,))))
        router.close(shutdown_engines=False)


# ---------------------------------------------------------------------------
# In-process fleet: routing, churn, crash-replay, watchdog
# ---------------------------------------------------------------------------


def _start_engine(path):
    eng = Engine(cfg())
    ready = threading.Event()
    threading.Thread(
        target=serve_socket, args=(eng, path),
        kwargs={"ready": ready.set}, daemon=True,
    ).start()
    assert ready.wait(30)
    return eng


class _Collector:
    """Per-job event sink with a first-hit gate (deterministic
    mid-sweep churn triggers)."""

    def __init__(self):
        self.events = []
        self.first_hit = threading.Event()

    def __call__(self, ev):
        self.events.append(ev)
        if ev.get("event") == "hit":
            self.first_hit.set()


@pytest.fixture()
def fleet2(tmp_path):
    engines = []
    paths = []
    for name in ("a", "b"):
        p = str(tmp_path / f"{name}.sock")
        engines.append(_start_engine(p))
        paths.append(p)
    router = FleetRouter(poll_s=0.5, defaults=cfg())
    links = [router.attach(p, f"eng{i}") for i, p in enumerate(paths)]
    try:
        yield router, links, engines
    finally:
        router.close(shutdown_engines=False)
        for eng in engines:
            eng.close(cancel=True)


class TestFleetInProcess:
    @pytest.mark.slow  # ~10 s on the tier-1 host; runs in CI via the
    # slow fleet soak step (-k filter includes "churn"); fleet routing
    # keeps default coverage via the other in-process fleet arms.
    def test_churn_mix_byte_parity(self, fleet2):
        """The §25 fast-tier contract: 2 engines × 4 churning tenants
        (plain / pause→resume / migrate / cancel) through the router —
        every surviving job's hit stream byte-identical to solo
        ``run_crack``."""
        router, _links, _engines = fleet2
        d_plain = planted_digests(WORDS, (0, -1))
        d_pr = planted_digests(BIG_WORDS, (0, 5, -1), decoys=21)
        d_mig = planted_digests(BIG_WORDS, (1, 6, -1), decoys=22)
        d_can = planted_digests(BIG_WORDS, (2, -1), decoys=23)
        cols = {j: _Collector() for j in ("plain", "pr", "mig", "can")}

        router.submit(job_doc("plain", WORDS, d_plain),
                      emit=cols["plain"])
        router.submit(job_doc("pr", BIG_WORDS, d_pr), emit=cols["pr"])
        router.submit(job_doc("mig", BIG_WORDS, d_mig),
                      emit=cols["mig"])
        router.submit(job_doc("can", BIG_WORDS, d_can),
                      emit=cols["can"])

        # Churn: pause 'pr' once it has streamed a hit, migrate 'mig'
        # to the other engine mid-sweep, cancel 'can'.
        assert cols["pr"].first_hit.wait(60)
        try:
            router.pause("pr")
        except FleetError:
            pass  # raced completion under host load
        assert router.wait("pr", timeout=60)
        assert cols["mig"].first_hit.wait(60)
        src = router.job("mig").link
        try:
            dst = next(
                l.engine_id for l in router.engines() if l is not src
            )
            router.migrate("mig", dst)
        except FleetError:
            pass  # raced completion under host load
        try:
            router.cancel("can")
            cancelled = True
        except FleetError:
            cancelled = False  # raced completion under host load
        # Resume the paused job (placement may move it — the
        # checkpoint is the contract either way).
        pr = router.job("pr")
        if pr.state == "paused":
            assert pr.checkpoint is not None
            router.resume("pr")

        for jid in ("plain", "pr", "mig"):
            assert router.wait(jid, timeout=300), jid
            assert router.job(jid).state == "done", (
                jid, router.job(jid).state, cols[jid].events[-2:]
            )
        assert router.wait("can", timeout=60)
        if cancelled:
            assert router.job("can").state == "cancelled"
            assert any(e.get("event") == "cancelled"
                       for e in cols["can"].events)
        else:
            assert router.job("can").state == "done"

        for jid, words, digs in (("plain", WORDS, d_plain),
                                 ("pr", BIG_WORDS, d_pr),
                                 ("mig", BIG_WORDS, d_mig)):
            res, want = solo_hits(words, digs)
            assert event_hits(cols[jid].events) == want, jid
            (done,) = [e for e in cols[jid].events
                       if e.get("event") == "done"]
            assert done["n_hits"] == res.n_hits

    def test_crash_replay_torn_socket_byte_parity(self, fleet2):
        """Engine death by torn socket: the router requeues the routed
        job onto the survivor from its last router-held checkpoint,
        with already-forwarded hits muted — the client stream stays
        exactly-once and byte-identical."""
        router, _links, _engines = fleet2
        digs = planted_digests(BIG_WORDS, (0, 3, -1))
        col = _Collector()
        router.submit(job_doc("c1", BIG_WORDS, digs), emit=col)
        assert col.first_hit.wait(60)
        router.job("c1").link.kill_socket()
        assert router.wait("c1", timeout=300)
        job = router.job("c1")
        assert job.state == "done", (job.state, col.events[-2:])
        _res, want = solo_hits(BIG_WORDS, digs)
        assert event_hits(col.events) == want
        fleet = router.stats()["fleet"]
        assert fleet["engines_alive"] == 1
        assert fleet["jobs_replayed"] >= 1

    @pytest.mark.slow
    def test_drain_empties_engine_and_jobs_finish(self, fleet2):
        """Slow-marked for the tier-1 budget (a drain re-sweeps the
        migrated job from its checkpoint); CI runs it in the fleet
        soak step."""
        router, _links, _engines = fleet2
        digs = planted_digests(BIG_WORDS, (0, -1), decoys=24)
        col = _Collector()
        router.submit(job_doc("dr", BIG_WORDS, digs), emit=col)
        assert col.first_hit.wait(60)
        src = router.job("dr").link
        ack = router.drain(src.engine_id)
        assert ack["jobs"] == 1 and src.draining
        assert router.wait("dr", timeout=300)
        assert router.job("dr").state == "done"
        _res, want = solo_hits(BIG_WORDS, digs)
        assert event_hits(col.events) == want
        # The drained engine took no new placements.
        col2 = _Collector()
        router.submit(job_doc("after", WORDS,
                              planted_digests(WORDS, (0,))), emit=col2)
        assert router.job("after").link is not src
        assert router.wait("after", timeout=120)

    def test_unknown_op_passthrough_and_errors(self, fleet2):
        router, _links, _engines = fleet2
        with pytest.raises(FleetError):
            router.pause("nope")
        with pytest.raises(FleetError):
            router.migrate("nope")

    def test_socket_front_end_serves_protocol(self, fleet2, tmp_path):
        """A serve client pointed at the ROUTER's socket works
        unmodified: submit → accepted / hit / done, stats answers with
        the fleet section, shutdown gets its bye (the session's
        outbound writer flushes before closing)."""
        from hashcat_a5_table_generator_tpu.runtime.fleet import (
            serve_fleet_socket,
        )

        router, _links, _engines = fleet2
        path = str(tmp_path / "router.sock")
        ready = threading.Event()
        threading.Thread(
            target=serve_fleet_socket, args=(router, path),
            kwargs={"ready": ready.set}, daemon=True,
        ).start()
        assert ready.wait(10)
        digs = planted_digests(WORDS, (0,))
        _res, want = solo_hits(WORDS, digs)
        with socket.socket(socket.AF_UNIX) as s:
            s.connect(path)
            f = s.makefile("rw", encoding="utf-8")
            f.write(json.dumps(job_doc("sf1", WORDS, digs)) + "\n")
            f.write(json.dumps({"op": "stats"}) + "\n")
            f.flush()
            events = []
            while not any(e.get("event") == "done" for e in events):
                events.append(json.loads(f.readline()))
            by = {}
            for e in events:
                by.setdefault(e["event"], []).append(e)
            assert by["accepted"][0]["engine"] in ("eng0", "eng1")
            assert event_hits(by.get("hit", ())) == want
            (st,) = by["stats"]
            assert st["fleet"]["engines_alive"] == 2
            f.write('{"op":"shutdown"}\n')
            f.flush()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                ev = json.loads(f.readline() or "{}")
                if ev.get("event") == "bye":
                    break
            else:
                pytest.fail("no bye before deadline")


@pytest.mark.slow
class TestWatchdog:
    """Slow-marked for the tier-1 budget (the watchdog must actually
    sit through poll_misses scrape timeouts); CI runs it in the fleet
    soak step."""

    def test_wedged_engine_declared_dead_and_job_replayed(self,
                                                          tmp_path):
        """Liveness is the stats op: a fake engine that accepts a job
        then stops answering scrapes is watchdog-killed, and its job
        crash-replays onto a real engine."""
        fake_path = str(tmp_path / "fake.sock")
        stop = threading.Event()
        #: stats served across ALL sessions — health scrapes reconnect
        #: after each failure, so a per-session count would hand every
        #: fresh connection one answer and the wedge would never show.
        served_stats = [0]

        def fake_engine():
            srv = socket.socket(socket.AF_UNIX)
            srv.bind(fake_path)
            srv.listen()
            srv.settimeout(0.2)
            conns = []
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                conns.append(conn)
                f = conn.makefile("rw", encoding="utf-8")

                def session(f=f):
                    for line in f:
                        doc = json.loads(line)
                        if doc.get("op") == "submit":
                            f.write(json.dumps({
                                "id": doc["id"], "event": "accepted",
                                "kind": "crack",
                            }) + "\n")
                            f.flush()
                        elif doc.get("op") == "stats":
                            served_stats[0] += 1
                            if served_stats[0] <= 1:
                                f.write('{"event":"stats"}\n')
                                f.flush()
                            # then: silence — the wedge

                threading.Thread(target=session, daemon=True).start()
            for c in conns:
                c.close()
            srv.close()

        threading.Thread(target=fake_engine, daemon=True).start()
        deadline = time.monotonic() + 10
        while not os.path.exists(fake_path):
            assert time.monotonic() < deadline
            time.sleep(0.05)

        real_path = str(tmp_path / "real.sock")
        eng = _start_engine(real_path)
        router = FleetRouter(poll_s=0.2, poll_misses=2,
                             defaults=cfg())
        try:
            fake = router.attach(fake_path, "fake")
            fake.scrape = {"resident_groups": [], "jobs_runnable": 0}
            real = router.attach(real_path, "real")
            # Pin the job onto the wedged engine.
            real.draining = True
            digs = planted_digests(WORDS, (0, -1))
            col = _Collector()
            router.submit(job_doc("w1", WORDS, digs), emit=col)
            assert router.job("w1").link is fake
            real.draining = False
            assert router.wait("w1", timeout=120)
            assert router.job("w1").state == "done"
            assert not fake.alive
            _res, want = solo_hits(WORDS, digs)
            assert event_hits(col.events) == want
        finally:
            stop.set()
            router.close(shutdown_engines=False)
            eng.close(cancel=True)


# ---------------------------------------------------------------------------
# Giant-job striping (PERF.md §31): scatter + k-way merge
# ---------------------------------------------------------------------------


@pytest.fixture()
def fleet2_split(tmp_path):
    """Two in-process engines behind a striping router: ``split="on"``
    scatters every placeable crack job regardless of the threshold."""
    engines = []
    paths = []
    for name in ("a", "b"):
        p = str(tmp_path / f"{name}.sock")
        engines.append(_start_engine(p))
        paths.append(p)
    router = FleetRouter(poll_s=0.5, defaults=cfg(), split="on")
    for i, p in enumerate(paths):
        router.attach(p, f"eng{i}")
    try:
        yield router, engines
    finally:
        router.close(shutdown_engines=False)
        for eng in engines:
            eng.close(cancel=True)


class TestSplitFleet:
    def test_auto_scatter_merge_byte_parity(self, fleet2_split):
        """The §31 default-tier contract: one job scattered as two
        disjoint pod stripes, per-shard streams k-way merged back into
        ONE (word,rank)-ordered exactly-once client stream — byte-
        identical to solo ``run_crack`` — with shard_done progress
        events and the parent ops guarded while split."""
        router, _engines = fleet2_split
        digs = planted_digests(BIG_WORDS, (0, 3, 7, -1))
        col = _Collector()
        router.submit(job_doc("sp", BIG_WORDS, digs), emit=col)
        # The parent has no single checkpoint/engine while split: the
        # churn ops must refuse it, and shard ids are router-internal.
        with pytest.raises(FleetError):
            router.pause("sp")
        with pytest.raises(FleetError):
            router.migrate("sp")
        with pytest.raises(FleetError):
            router.resume("sp::s0")
        with pytest.raises(FleetError):
            router.cancel("sp::s1")
        # Stripes DO rebalance: migrating one mid-range rides the same
        # acked-boundary + mute discipline as the crash path and tells
        # the parent's client (range_reassign).
        assert col.first_hit.wait(60)
        try:
            router.migrate("sp::s1")
            migrated = True
        except FleetError:
            migrated = False  # raced completion under host load
        assert router.wait("sp", timeout=300)
        assert router.job("sp").state == "done", col.events[-2:]
        res, want = solo_hits(BIG_WORDS, digs)
        assert event_hits(col.events) == want
        shard_done = [e for e in col.events
                      if e.get("event") == "shard_done"]
        assert {e["shard"] for e in shard_done} == {0, 1}
        assert all(e["shards"] == 2 for e in shard_done)
        (done,) = [e for e in col.events if e.get("event") == "done"]
        assert done["n_hits"] == res.n_hits
        assert done["n_emitted"] == res.n_emitted
        fleet = router.stats()["fleet"]
        assert fleet["jobs_split"] == 1
        if migrated:
            assert fleet["shards_reassigned"] >= 1
            assert any(e.get("event") == "range_reassign"
                       and e["shard"] == 1
                       for e in col.events)

    def test_explicit_split_op_solo_to_split(self, fleet2):
        """The explicit ``split`` op mid-run (solo→split on the wire):
        a running UNSPLIT job parks, its solo checkpoint seeds both
        shards with forwarded hits muted, and the client stream stays
        exactly-once byte-identical to solo."""
        router, _links, _engines = fleet2
        digs = planted_digests(BIG_WORDS, (0, 4, -1), decoys=25)
        col = _Collector()
        router.submit(job_doc("xs", BIG_WORDS, digs), emit=col)
        with pytest.raises(FleetError):
            router.split("nope")  # unknown job fails loudly
        assert col.first_hit.wait(60)
        prefix = event_hits(col.events)
        try:
            ack = router.split("xs")
        except FleetError:
            ack = None  # raced completion under host load
        if ack is not None:
            assert ack["shards"] == 2
            with pytest.raises(FleetError):
                router.split("xs")  # already split
            assert router.stats()["fleet"]["jobs_split"] == 1
        assert router.wait("xs", timeout=300)
        assert router.job("xs").state == "done", col.events[-2:]
        _res, want = solo_hits(BIG_WORDS, digs)
        got = event_hits(col.events)
        assert got == want
        # Run-1's forwarded hits are a PREFIX: the scatter muted them.
        assert got[:len(prefix)] == prefix


# ---------------------------------------------------------------------------
# Spawned multi-process fleet (slow tier): SIGKILL soak + affinity
# ---------------------------------------------------------------------------


def _spawned_fleet(tmp_path, n=2, place="affinity", split=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("A5GEN_FAULTS", None)
    specs = spawn_engines(
        n, str(tmp_path / "engines"),
        engine_args=["--lanes", "64", "--blocks", "16",
                     "--superstep", "1",
                     "--schema-cache", str(tmp_path / "cache")],
        env=env,
    )
    router = FleetRouter(place=place, poll_s=0.5, defaults=cfg(),
                         split=split)
    for sock_path, eid, proc in specs:
        router.attach(sock_path, eid, proc=proc, timeout=300)
    return router, specs


@pytest.mark.slow
class TestSpawnedFleet:
    def test_kill_one_engine_soak_byte_parity(self, tmp_path):
        """The §25 top-tier contract, full strength: 2 engine
        PROCESSES × 4 churning tenants through the router; one engine
        is SIGKILLed mid-sweep and every routed job crash-replays onto
        the survivor — per-job hit streams byte-identical to solo
        ``run_crack``, exactly-once."""
        soak_words = WORDS * 40  # slow tier: generous churn windows
        router, specs = _spawned_fleet(tmp_path)
        try:
            jobs = {}
            for i in range(4):
                digs = planted_digests(soak_words, (i, 5 + i, -1),
                                       decoys=20 + i)
                col = _Collector()
                jobs[f"j{i}"] = (digs, col)
                router.submit(job_doc(f"j{i}", soak_words, digs),
                              emit=col)
            # Light churn on the side: pause+resume one tenant.
            assert jobs["j0"][1].first_hit.wait(120)
            try:
                router.pause("j0")
            except FleetError:
                pass  # raced completion under host load
            assert router.wait("j0", timeout=120)
            if router.job("j0").state == "paused":
                router.resume("j0")
            # SIGKILL the engine carrying j1 once it is mid-sweep.
            assert jobs["j1"][1].first_hit.wait(120)
            victim = router.job("j1").link
            os.kill(victim.proc.pid, signal.SIGKILL)
            for jid, (digs, col) in jobs.items():
                assert router.wait(jid, timeout=600), jid
                assert router.job(jid).state == "done", (
                    jid, router.job(jid).state, col.events[-2:]
                )
                res, want = solo_hits(soak_words, digs)
                assert event_hits(col.events) == want, jid
                (done,) = [e for e in col.events
                           if e.get("event") == "done"]
                assert done["n_hits"] == res.n_hits
            fleet = router.stats()["fleet"]
            assert fleet["engine_deaths"] == 1
            assert fleet["jobs_replayed"] >= 1
            assert victim.proc.poll() == -signal.SIGKILL
        finally:
            router.close(shutdown_engines=True)

    def test_split_sigkill_reassigns_from_acked_boundary(self,
                                                         tmp_path):
        """The §31 crash contract, full strength: a 2-engine split job
        loses one engine PROCESS to SIGKILL mid-range; the router
        reassigns the dead shard's stripe onto the survivor from its
        last acked boundary (range_reassign), already-forwarded hits
        muted — the merged client stream stays exactly-once and
        byte-identical to solo, with run-1's hits a strict prefix."""
        soak_words = WORDS * 40  # slow tier: generous kill window
        router, specs = _spawned_fleet(tmp_path, split="on")
        try:
            digs = planted_digests(soak_words, (0, 5, 9, -1))
            col = _Collector()
            router.submit(job_doc("g1", soak_words, digs), emit=col)
            assert col.first_hit.wait(120)
            prefix = event_hits(col.events)
            victim = router.job("g1::s0").link
            os.kill(victim.proc.pid, signal.SIGKILL)
            assert router.wait("g1", timeout=600)
            assert router.job("g1").state == "done", col.events[-2:]
            res, want = solo_hits(soak_words, digs)
            got = event_hits(col.events)
            assert got == want
            # Run-1 is a prefix: the merge never re-released or
            # reordered hits forwarded before the kill.
            assert got[:len(prefix)] == prefix
            reassigns = [e for e in col.events
                         if e.get("event") == "range_reassign"]
            assert reassigns and reassigns[0]["shards"] == 2
            assert reassigns[0]["from"] == victim.engine_id
            (done,) = [e for e in col.events
                       if e.get("event") == "done"]
            assert done["n_hits"] == res.n_hits
            assert done["n_emitted"] == res.n_emitted
            fleet = router.stats()["fleet"]
            assert fleet["engine_deaths"] == 1
            assert fleet["shards_reassigned"] >= 1
            assert fleet["jobs_split"] == 1
            assert victim.proc.poll() == -signal.SIGKILL
        finally:
            router.close(shutdown_engines=True)

    def test_affinity_compile_reuse_vs_round_robin(self, tmp_path):
        """The §25 affinity instrument: two compatible jobs through a
        2-engine fleet land on ONE engine under affinity placement —
        one shared program build serves both (step-cache counter, plus
        the engine's one trivial accumulator jit) — while the
        round-robin control arm splits them and every engine pays its
        own builds: fleet-total compiles exactly double.  Per-process
        step caches (spawned engines) are what make the counter
        honest."""

        def run_arm(place, subdir):
            router, _specs = _spawned_fleet(tmp_path / subdir,
                                            place=place)
            try:
                digs = planted_digests(WORDS, (0, -1))
                cols = [_Collector(), _Collector()]
                placed = []
                for i, col in enumerate(cols):
                    ack = router.submit(job_doc(f"a{i}", WORDS, digs),
                                        emit=col)
                    placed.append(ack["engine"])
                for i in range(2):
                    assert router.wait(f"a{i}", timeout=600)
                    assert router.job(f"a{i}").state == "done"
                stats = router.stats()
                _res, want = solo_hits(WORDS, digs)
                for col in cols:
                    assert event_hits(col.events) == want
                return placed, stats
            finally:
                router.close(shutdown_engines=True)

        placed_aff, stats_aff = run_arm("affinity", "aff")
        placed_rr, stats_rr = run_arm("round-robin", "rr")
        # Affinity co-locates the compatible pair; the control splits.
        assert len(set(placed_aff)) == 1
        assert len(set(placed_rr)) == 2
        # One engine's builds serve both jobs under affinity (the
        # second job rides the step cache); round-robin compiles the
        # identical set on BOTH engines — exactly double fleet-wide.
        assert stats_rr["programs_compiled"] == \
            2 * stats_aff["programs_compiled"]
        assert stats_aff["program_cache_hits"] >= 1
        assert stats_rr["program_cache_hits"] == 0


@pytest.mark.slow
def test_bench_fleet_ab_record_shape():
    """The §25 passthrough instrument end-to-end: both arms run, the
    parity gate holds inside the bench, and the JSON record carries
    the wall ratio the acceptance criterion reads."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--fleet-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "600", "--serve-jobs", "3"],
        capture_output=True, timeout=540, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "fleet_ab"
    assert rec["jobs"] == 3
    assert len(rec["direct"]["jobs"]) == 3
    assert len(rec["routed"]["jobs"]) == 3
    emitted = {j["n_emitted"] for j in rec["direct"]["jobs"]}
    emitted |= {j["n_emitted"] for j in rec["routed"]["jobs"]}
    assert len(emitted) == 1 and emitted.pop() > 0
    assert rec["wall_ratio"] > 0
    assert "overhead_pct" in rec


@pytest.mark.slow
def test_bench_split_ab_record_shape():
    """The §31 striping instrument end-to-end: both arms run, the
    byte-exact merged-stream parity gate holds inside the bench, and
    the JSON record carries the speedup and merge-overhead share the
    acceptance criteria read."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--split-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "4000"],
        capture_output=True, timeout=540, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "split_ab"
    assert rec["split"]["engines"] == 2
    assert rec["split"]["jobs_split"] == 2  # warm + measured
    assert rec["split"]["shard_done_events"] == 2
    assert rec["split"]["n_emitted"] == rec["solo"]["n_emitted"] > 0
    assert rec["split"]["hits"] == rec["solo"]["hits"] > 0
    assert rec["speedup"] > 0
    # The merge is bookkeeping, not a pipeline stage: §31 pins the
    # overhead share; the in-bench ceiling stays loose vs the 10%
    # acceptance bar to keep tiny-geometry CI runs honest but stable.
    assert rec["merge_overhead_share"] < 0.10
    assert rec["host_cpus"] >= 1

"""The driver-facing bench contract (README "Maintain bench.py"): one JSON
line on stdout with metric/value/unit/vs_baseline, whatever the platform.
Runs the worker directly on the CPU backend at a tiny geometry — the
orchestrator's kill-timeout machinery is exercised implicitly every round
by the driver; what must never regress silently is the record shape and
the worker's ability to produce a number."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_worker_emits_one_json_record():
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--platform", "cpu",
         "--lanes", "4096", "--blocks", "64", "--words", "400",
         "--seconds", "1", "--batches", "2"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["metric"] == "md5_candidate_hashes_per_sec_per_chip"
    assert rec["unit"] == "hashes/sec"
    assert rec["value"] > 0
    assert rec["platform"] == "cpu"
    assert rec["launches"] >= 2  # bounded-in-flight loop actually ran


def test_worker_respects_block_layout_flag():
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--platform", "cpu",
         "--lanes", "4096", "--blocks", "64", "--words", "400",
         "--seconds", "1", "--batches", "2", "--block-layout", "stride"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"(stride 64)" in r.stderr
    rec = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert rec["value"] > 0

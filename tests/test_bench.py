"""The driver-facing bench contract (README "Maintain bench.py"): one JSON
line on stdout with metric/value/unit/vs_baseline, whatever the platform.
Runs the worker directly on the CPU backend at a tiny geometry — the
orchestrator's kill-timeout machinery is exercised implicitly every round
by the driver; what must never regress silently is the record shape and
the worker's ability to produce a number."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_worker_emits_one_json_record():
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--platform", "cpu",
         "--lanes", "4096", "--blocks", "64", "--words", "400",
         "--seconds", "1", "--batches", "2"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["metric"] == "md5_candidate_hashes_per_sec_per_chip"
    assert rec["unit"] == "hashes/sec"
    assert rec["value"] > 0
    assert rec["platform"] == "cpu"
    assert rec["launches"] >= 2  # bounded-in-flight loop actually ran


def test_worker_respects_block_layout_flag():
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--platform", "cpu",
         "--lanes", "4096", "--blocks", "64", "--words", "400",
         "--seconds", "1", "--batches", "2", "--block-layout", "stride"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"(stride 64)" in r.stderr
    rec = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert rec["value"] > 0


def test_tpu_last_record_save_and_attach(tmp_path, monkeypatch):
    """Bench resilience (VERDICT r5 #2): a successful accelerator record
    overwrites the committed last-good file; a CPU-fallback or error
    record embeds it as the labeled `last_tpu` field."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)

    path = tmp_path / "BENCH_TPU_LAST.json"
    monkeypatch.setattr(bench, "TPU_LAST_PATH", str(path))
    rec = {
        "metric": "md5_candidate_hashes_per_sec_per_chip",
        "value": 5.41e8, "unit": "hashes/sec", "lanes": 1 << 22,
        "blocks": 32768, "arm": "pallas", "kernel": "scalar-bitmask",
        "platform": "tpu", "device_kind": "TPU v5 lite",
        "vs_baseline": 0.0541,  # non-whitelisted keys must not persist
    }
    bench.save_tpu_last(rec)
    saved = json.loads(path.read_text())
    assert saved["value"] == 5.41e8
    assert saved["platform"] == "tpu"
    assert "timestamp" in saved
    assert "vs_baseline" not in saved

    cpu_rec = {"value": 7.4e6, "platform": "cpu"}
    bench.attach_tpu_evidence(cpu_rec)
    assert cpu_rec["last_tpu"]["value"] == 5.41e8

    # Missing/corrupt file: the record passes through unlabeled.
    path.write_text("{not json")
    clean = {"value": 1.0}
    bench.attach_tpu_evidence(clean)
    assert "last_tpu" not in clean


def test_committed_tpu_last_is_valid():
    """The checked-in BENCH_TPU_LAST.json (seeded from the round-5
    on-chip session, PERF.md §11) must stay parseable with the fields
    the driver artifact embeds."""
    rec = json.loads((REPO / "BENCH_TPU_LAST.json").read_text())
    for key in ("metric", "value", "unit", "platform", "device_kind",
                "arm", "timestamp"):
        assert key in rec, key
    assert rec["platform"] != "cpu"
    assert rec["value"] > 0


def test_parser_has_stride_ab_and_init_retry_budget():
    """New knobs (PERF.md §17): the stride/emission A/B arm and the
    orchestrator's cap on cumulative pre-init retry wall."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    args = bench._build_bench_parser().parse_args([])
    assert args.stride_ab is False
    assert args.init_retry_budget == 240.0


def test_parser_has_pipeline_ab():
    """The §18 pipeline A/B arm rides the same parser contract as
    --superstep-ab (default-off flag, §4c geometry defaulting)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    args = bench._build_bench_parser().parse_args([])
    assert args.pipeline_ab is False
    assert bench._build_bench_parser().parse_args(["--pipeline-ab"]).pipeline_ab


def test_parser_has_split_ab_and_churn_cross():
    """The §31 fleet-striping arms ride the same parser contract as the
    other A/B flags (default-off; --split-engines sizes the fleet)."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    args = bench._build_bench_parser().parse_args([])
    assert args.split_ab is False
    assert args.churn_cross is False
    assert args.split_engines == 2
    args = bench._build_bench_parser().parse_args(
        ["--split-ab", "--split-engines", "3"]
    )
    assert args.split_ab and args.split_engines == 3


def test_compare_last_tpu_skips_partial_matrix(tmp_path, monkeypatch,
                                               capsys):
    """A partial autotune matrix is a checkpoint, not a best-geometry
    measurement: --compare-last-tpu must refuse it as a baseline (and
    say so) instead of rendering an inflated verdict against it."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)

    path = tmp_path / "BENCH_TPU_LAST.json"
    monkeypatch.setattr(bench, "TPU_LAST_PATH", str(path))
    partial = {
        "metric": "md5_candidate_hashes_per_sec_per_chip",
        "value": 9.9e9, "unit": "hashes/sec", "platform": "tpu",
        "device_kind": "TPU v5 lite", "partial_matrix": True,
        "timestamp": "2026-01-01T00:00:00Z",
    }
    path.write_text(json.dumps(partial))
    bench.compare_last_tpu(1.0e8)
    err = capsys.readouterr().err
    assert "PARTIAL autotune matrix" in err
    assert "skipped as baseline" in err
    # No verdict line against the rejected record — the baseline slot
    # reads as empty.
    assert "verdict" not in err
    assert "no usable BENCH_TPU_LAST.json" in err

    # A completed record still compares (and the saver whitelists the
    # partial_matrix flag through, so a later partial save is visible).
    del partial["partial_matrix"]
    path.write_text(json.dumps(partial))
    bench.compare_last_tpu(1.0e8)
    err = capsys.readouterr().err
    assert "verdict" in err and "BEHIND" in err
    bench.save_tpu_last({**partial, "partial_matrix": True})
    assert json.loads(path.read_text())["partial_matrix"] is True


import pytest  # noqa: E402


@pytest.mark.slow
def test_stride_ab_record_shape():
    """--stride-ab: one JSON record with per-arm hashes/s AND the
    budget-counter ops/candidate, plus the winner and the
    KERNEL_BUDGETS cross-reference."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--stride-ab",
         "--platform", "cpu", "--words", "300", "--seconds", "1",
         "--batches", "2"],
        capture_output=True, timeout=420, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    rec = json.loads(r.stdout.decode().strip().splitlines()[-1])
    assert rec["metric"] == "stride_emit_ab"
    assert rec["budget_file"] == "KERNEL_BUDGETS.json"
    assert rec["winner"] in rec["arms"]
    assert rec["emit_default"] in ("perslot", "bytescan")
    for name in ("stride128-perslot", "stride128-bytescan",
                 "stride256-perslot", "stride256-bytescan"):
        arm = rec["arms"][name]
        assert arm["value"] > 0
        assert arm["ops_per_candidate"] > 0
        assert arm["path"] in ("pallas", "xla")
    # The per-slot scheme must not count MORE ops than bytescan at the
    # same stride — the whole point of the rewrite.
    assert (rec["arms"]["stride128-perslot"]["ops_per_candidate"]
            < rec["arms"]["stride128-bytescan"]["ops_per_candidate"])

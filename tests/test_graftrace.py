"""graftrace (PERF.md §26): thread-topology & lock-discipline static
analysis, plus the deterministic-interleaving race harness.

Static half: every check must both FLAG its broken fixture and stay
quiet on the clean twin (``tests/lint_fixtures/trace/``), the shipped
runtime must analyze clean (the lint.sh layer-5 gate as a test), and
the grandfather allowlist must stay LIVE (an entry whose finding no
longer fires must be deleted — shrink-only).

Dynamic half: the known race windows get replayable schedule tests
through :class:`tools.graftrace.interleave.Interleaver` — threads park
at the existing fault-injection seams and the test releases them in an
explicit order, replacing sleep-and-hope:

* staging-to-active cancel (``Engine.close(cancel=True)`` racing a
  build between worker completion and activation),
* death-racing-submit (an engine dying with the dispatch un-acked must
  be owned by the dispatcher ONCE, never also crash-replayed),
* watchdog-vs-pause (a stalled drive loop must not look dead to the
  fleet health scrapes — the dedicated health connection's contract).

Tier-1 budget: the race tests share the suite's 64×16 geometry (the
process step cache serves them) and gate on events, never sleeps; the
multi-seed schedule sweep is slow-marked.
"""

import json
import pathlib
import subprocess
import sys
import threading
import time

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftrace import (  # noqa: E402
    ALL_CHECKS,
    analyze_paths,
    analyze_sources,
)
from tools.graftrace.allowlist import ALLOWLIST  # noqa: E402
from tools.graftrace.cli import DEFAULT_PATHS  # noqa: E402
from tools.graftrace.interleave import Interleaver  # noqa: E402
from tools.graftrace.report import to_markdown  # noqa: E402

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "lint_fixtures" \
    / "trace"
CODES = sorted(ALL_CHECKS)
RUNTIME_PATHS = [str(REPO_ROOT / p) for p in DEFAULT_PATHS]


# ---------------------------------------------------------------------------
# The static model: fixture corpus
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("code", CODES)
def test_check_flags_its_hazard(code):
    path = FIXTURE_DIR / f"{code.lower()}_flag.py"
    findings, _models = analyze_paths([str(path)], select=[code])
    assert findings, f"{code} did not flag its broken fixture"
    assert all(f.code == code for f in findings)


@pytest.mark.parametrize("code", CODES)
def test_check_passes_the_clean_twin(code):
    path = FIXTURE_DIR / f"{code.lower()}_ok.py"
    findings, _models = analyze_paths([str(path)], select=[code])
    assert not findings, (
        f"{code} false-positived on its clean twin: "
        + "; ".join(f.render() for f in findings)
    )


@pytest.mark.parametrize("code", CODES)
def test_fixture_pair_exists(code):
    for kind in ("flag", "ok"):
        assert (FIXTURE_DIR / f"{code.lower()}_{kind}.py").is_file()


def test_annotation_guarded_fixture_is_clean():
    """guard=/owner= annotations silence writes the lexical scan
    cannot prove (the declared-guard grammar)."""
    findings, _ = analyze_paths(
        [str(FIXTURE_DIR / "gt001_ann_ok.py")]
    )
    assert not findings, "\n".join(f.render() for f in findings)


def test_unknown_guard_name_is_a_finding():
    """A guard= naming no lock attribute is flagged, not trusted — a
    typo must not silently disarm the check."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "        threading.Thread(target=self._w).start()\n"
        "    def _w(self):\n"
        "        self.n += 1  # graftrace: guard=_lokc\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
    )
    findings, _ = analyze_sources([(src, "virt/c.py")], select=["GT001"])
    assert findings and "names no lock attribute" in findings[0].message


def test_nonblocking_get_is_not_a_wait_cycle():
    """Only a block-forever ``get()`` can deadlock: the non-blocking
    drain forms (``get_nowait``/``get(False)``/``get(block=False)``)
    and any-timeout forms must not trip GT003 — while ``get(True)`` /
    ``get(timeout=None)`` still do."""
    template = (
        "import queue\n"
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._q = queue.Queue()\n"
        "        threading.Thread(target=self._w).start()\n"
        "    def _w(self):\n"
        "        self._q.put(1)\n"
        "        self._q.{call}\n"
    )
    for call in ("get_nowait()", "get(False)", "get(block=False)",
                 "get(timeout=1.0)", "get(True, 0.5)"):
        findings, _ = analyze_sources(
            [(template.format(call=call), "virt/p.py")], select=["GT003"]
        )
        assert not findings, f"{call} false-positived GT003"
    for call in ("get()", "get(True)", "get(block=True)",
                 "get(timeout=None)"):
        findings, _ = analyze_sources(
            [(template.format(call=call), "virt/p.py")], select=["GT003"]
        )
        assert findings, f"{call} should still flag GT003"


def test_requeue_deadlock_fixture_names_the_cycle():
    """The acceptance bar: the fleet requeue-worker deadlock, written
    as a fixture, is caught MECHANICALLY with the wait-for cycle
    spelled out."""
    findings, _ = analyze_paths(
        [str(FIXTURE_DIR / "gt003_flag.py")], select=["GT003"]
    )
    assert len(findings) == 1
    msg = findings[0].message
    assert "_reader" in msg and "_reply" in msg


def test_repo_runtime_is_clean():
    """The gate scripts/lint.sh layer 5 enforces, as a test: the
    threaded runtime must analyze clean under the shipped allowlist."""
    findings, models = analyze_paths(RUNTIME_PATHS)
    assert not findings, "\n".join(f.render() for f in findings)
    # The model actually discovered the threaded classes (a vacuous
    # pass would certify nothing).
    threaded = {m.name for m in models if m.entries}
    assert {"Engine", "FleetRouter", "EngineLink",
            "ChunkCompiler", "Autoscaler"} <= threaded


def test_autoscaler_queue_discipline_fixture_pair():
    """The §27 autoscaler's GT003 story, as fixtures: a control loop
    blocking on its own spawn-ack queue is a wait-for self-cycle
    (flagged), while the shipped discipline — Event-paced ticks,
    synchronous spawn, caller-produced request queue drained
    non-blocking — analyzes clean."""
    findings, _ = analyze_paths(
        [str(FIXTURE_DIR / "gt003_autoscale_flag.py")], select=["GT003"]
    )
    assert len(findings) == 1
    assert "_loop" in findings[0].message
    assert "_spawned" in findings[0].message
    findings, _ = analyze_paths(
        [str(FIXTURE_DIR / "gt003_autoscale_ok.py")]
    )
    assert not findings, "\n".join(f.render() for f in findings)


def test_allowlist_is_live_and_shrink_only():
    """Every grandfather entry must still match a real finding: once
    the pattern is fixed, the entry MUST be deleted (shrink-only)."""
    findings, _ = analyze_paths(RUNTIME_PATHS, use_allowlist=False)
    for (suffix, key), why in ALLOWLIST.items():
        assert why.strip(), f"allowlist entry {key} needs a reason"
        assert any(
            f.path.replace("\\", "/").endswith(suffix) and f.key == key
            for f in findings
        ), (
            f"allowlist entry ({suffix}, {key}) matches no finding — "
            "the pattern was fixed; delete the entry"
        )


def test_gt004_extraction_surfaces_are_live():
    """GT004 skips silently when either session class is missing from
    the file set (correct for partial scans) — so renaming
    _JsonlSession/_RouterSession or gutting their _handle op tables
    must trip THIS pin, not quietly disarm the gate."""
    import ast as _ast

    from tools.graftrace.passthrough import (
        ENGINE_SESSION,
        ROUTER_SESSION,
        _handle_ops,
    )

    found = {}
    for rel in ("hashcat_a5_table_generator_tpu/runtime/engine.py",
                "hashcat_a5_table_generator_tpu/runtime/fleet.py"):
        tree = _ast.parse((REPO_ROOT / rel).read_text())
        for node in _ast.walk(tree):
            if isinstance(node, _ast.ClassDef) and node.name in (
                ENGINE_SESSION, ROUTER_SESSION
            ):
                found[node.name] = _handle_ops(node)[0]
    assert set(found) == {ENGINE_SESSION, ROUTER_SESSION}, (
        f"GT004 anchor class missing/renamed: found {sorted(found)} — "
        "update tools/graftrace/passthrough.py in the same change"
    )
    assert "submit" in found[ENGINE_SESSION]
    assert found[ROUTER_SESSION], "router op table extracted empty"


def test_topology_report_shows_threads_and_guards():
    _findings, models = analyze_paths(RUNTIME_PATHS)
    md = to_markdown(models)
    assert "`Engine`" in md and "`FleetRouter`" in md
    assert "_lock" in md  # the guard column is populated
    assert "lock order" in md  # EngineLink's _ctl_lock -> _wlock edge
    # The review surface must be honest: declared single-writers and
    # grandfathered attrs never render like unguarded hazards.
    assert "declared owner=collector" in md  # Engine._admit_ex
    assert "allowlisted" in md  # _RouterSession._dead
    # graftrace eats its own dogfood: tools/ (the interleave harness
    # included) is part of the default scan.
    assert any(m.name == "Interleaver" for m in models)


def test_cli_exit_codes_and_artifacts(tmp_path):
    """0 clean / 1 findings / 2 usage error through the real CLI, plus
    the --report/--metrics-json artifact shapes CI uploads."""
    report = tmp_path / "topo.md"
    metrics = tmp_path / "metrics.json"
    clean = subprocess.run(
        [sys.executable, "-m", "tools.graftrace",
         *DEFAULT_PATHS,
         "--report", str(report), "--metrics-json", str(metrics)],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "graftrace thread topology" in report.read_text()
    payload = json.loads(metrics.read_text())["graftrace"]
    assert payload["classes_threaded"] >= 4
    assert payload["findings"] == 0
    flag = subprocess.run(
        [sys.executable, "-m", "tools.graftrace",
         str(FIXTURE_DIR / "gt001_flag.py")],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert flag.returncode == 1
    assert "GT001" in flag.stdout
    usage = subprocess.run(
        [sys.executable, "-m", "tools.graftrace", "--select", "GT999"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert usage.returncode == 2


# ---------------------------------------------------------------------------
# The interleave harness
# ---------------------------------------------------------------------------


def _poll(predicate, timeout=20.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() >= deadline:
            return False
        time.sleep(interval)
    return True


def test_interleaver_parks_and_releases_in_order():
    """Pure-harness contract: held points park arrivals, releases
    resume oldest-first, nothing times out."""
    from hashcat_a5_table_generator_tpu.runtime import faults

    with Interleaver() as il:
        il.hold("serve.client")
        done = []

        def worker(i):
            assert faults.ACTIVE is not None
            faults.ACTIVE.fire("serve.client")
            done.append(i)

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(3)
        ]
        for t in threads:
            t.start()
        il.await_arrival("serve.client", count=3)
        assert done == []
        # Back-to-back releases resume DISTINCT threads: a released
        # thread lingers in the parked map until it wakes, and a
        # double-count here would strand the second thread.
        assert il.release("serve.client", 1) == 1
        assert il.release("serve.client", 1) == 1
        assert _poll(lambda: len(done) == 2)
        assert il.release_all("serve.client") == 1
        for t in threads:
            t.join(timeout=10)
        assert sorted(done) == [0, 1, 2]
        assert il.timeouts == []
    with pytest.raises(ValueError):
        Interleaver().hold("not.a.point")
    # One-shot: a reused instance would run unscheduled (the _closing
    # latch makes _arrive a pass-through) — re-entry fails loudly.
    with pytest.raises(RuntimeError, match="one-shot"):
        il.__enter__()


# ---------------------------------------------------------------------------
# Race-window replay tests (the §20/§22/§25 windows, scheduled)
# ---------------------------------------------------------------------------


def _engine_fixtures():
    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from tests.test_superstep import LEET, WORDS, oracle_lines
    import hashlib

    spec = AttackSpec(mode="default", algo="md5")
    oracle = oracle_lines(spec, LEET, WORDS)
    planted = sorted({oracle[0], oracle[-1]})
    digests = [hashlib.md5(c).digest() for c in planted]
    digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(8)]
    return spec, LEET, WORDS, digests


def test_race_staging_to_active_cancel():
    """§22 window: ``close(cancel=True)`` lands while the admission
    worker is mid-build — the slot exists in no list yet, and the
    ``_cancel_all`` flag must still retire it before any machine tick.
    The schedule is explicit: the build PARKS at the admission.build
    seam, the cancel runs, then the build completes."""
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

    spec, leet, words, digests = _engine_fixtures()
    with Interleaver() as il:
        il.hold("admission.build")
        eng = Engine(SweepConfig(lanes=64, num_blocks=16, superstep=1))
        job = eng.submit(spec, leet, words, digests)
        il.await_arrival("admission.build")
        closer = threading.Thread(
            target=lambda: eng.close(cancel=True), daemon=True
        )
        closer.start()
        # Deterministic trigger: close() has marked the in-flight
        # build cancelled (the event, not a sleep) before we let the
        # build finish.
        assert job._cancel_req.wait(timeout=20)
        il.unhold("admission.build")
        il.release_all("admission.build")
        closer.join(timeout=60)
        assert not closer.is_alive()
        assert il.timeouts == []
    assert job.state == "cancelled"
    assert job.wait(timeout=1)


def test_race_death_during_unacked_submit_single_owner(tmp_path):
    """§25 window: the engine dies while the submit dispatch is still
    un-acked.  The dispatching thread owns the failure — the death
    handler must NOT also requeue (double ownership would run a ghost
    sweep).  The fake engine sequences the race exactly: it tears the
    op connection only after reading the submit, so the death always
    lands mid-dispatch."""
    import json as _json
    import socket

    from hashcat_a5_table_generator_tpu.runtime import telemetry
    from hashcat_a5_table_generator_tpu.runtime.fleet import (
        FleetError,
        FleetRouter,
    )
    from tests.test_fleet import _Collector, cfg, job_doc, \
        planted_digests
    from tests.test_superstep import WORDS

    path = str(tmp_path / "fake.sock")
    stop = threading.Event()

    def fake_engine():
        srv = socket.socket(socket.AF_UNIX)
        srv.bind(path)
        srv.listen()
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue

            def session(conn=conn):
                with conn:
                    f = conn.makefile("rw", encoding="utf-8")
                    for line in f:
                        doc = _json.loads(line)
                        if doc.get("op") == "stats":
                            f.write('{"event":"stats"}\n')
                            f.flush()
                        elif doc.get("op") == "submit":
                            return  # tear mid-dispatch: no ack ever

            threading.Thread(target=session, daemon=True).start()
        srv.close()

    threading.Thread(target=fake_engine, daemon=True).start()
    assert _poll(lambda: pathlib.Path(path).exists())

    replayed0 = int(telemetry.counter("fleet.jobs_replayed").value)
    router = FleetRouter(poll_s=0, defaults=cfg())
    try:
        link = router.attach(path, "fake")
        col = _Collector()
        digs = planted_digests(WORDS, (0,))
        with pytest.raises(FleetError):
            router.submit(job_doc("race1", WORDS, digs), emit=col)
        # The reader observed the torn socket and ran death handling.
        assert _poll(lambda: not link.alive)
        # Single ownership: the un-acked job was NOT crash-replayed —
        # no requeue dispatch, no forwarded failure, table entry
        # dropped so the client can retry under the same id.
        time.sleep(0.2)  # grace for a (buggy) requeue to surface
        assert int(
            telemetry.counter("fleet.jobs_replayed").value
        ) == replayed0
        assert col.events == []
        with pytest.raises(FleetError):
            router.job("race1")
    finally:
        stop.set()
        router.close(shutdown_engines=False)


def test_race_watchdog_vs_stalled_drive():
    """§23/§25 window: an engine whose drive loop is stalled mid-
    superstep (here: parked at the superstep.fetch seam) must keep
    answering health scrapes on the dedicated connection — a busy
    engine must never be declared dead by the watchdog.  The stall is
    a schedule gate, not a sleep."""
    from tests.test_fleet import (
        _Collector,
        _start_engine,
        cfg,
        event_hits,
        job_doc,
        planted_digests,
        solo_hits,
    )
    from hashcat_a5_table_generator_tpu.runtime.fleet import FleetRouter
    from tests.test_superstep import WORDS
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        sock = str(pathlib.Path(tmp) / "eng.sock")
        with Interleaver() as il:
            il.hold("superstep.fetch")
            eng = _start_engine(sock)
            router = FleetRouter(poll_s=0, poll_misses=2,
                                 defaults=cfg())
            try:
                link = router.attach(sock, "eng0")
                digs = planted_digests(WORDS, (0, -1))
                col = _Collector()
                router.submit(job_doc("w1", WORDS, digs), emit=col)
                il.await_arrival("superstep.fetch")
                # The drive is parked mid-superstep; every scrape must
                # still answer (the health connection's whole point).
                for _ in range(3):
                    router._scrape(link)
                assert link.misses == 0
                assert link.alive
                il.unhold("superstep.fetch")
                il.release_all("superstep.fetch")
                assert router.wait("w1", timeout=120)
                assert router.job("w1").state == "done"
                assert il.timeouts == []
                _res, want = solo_hits(WORDS, digs)
                assert event_hits(col.events) == want
            finally:
                router.close(shutdown_engines=False)
                eng.close(cancel=True)


def _seeded_schedule_run(seed):
    """Two fusable tenants under the seeded governor: whatever order
    the scheduler releases the dispatch/fetch/pump/build steps in,
    per-job hit streams must match the solo baseline byte-for-byte."""
    from hashcat_a5_table_generator_tpu.runtime.engine import Engine
    from hashcat_a5_table_generator_tpu.runtime.sweep import (
        Sweep,
        SweepConfig,
    )
    from tests.test_superstep import hit_tuples

    spec, leet, words, digests = _engine_fixtures()
    config = SweepConfig(lanes=64, num_blocks=16, superstep=1)
    want = hit_tuples(
        Sweep(spec, leet, words, digests, config=config).run_crack()
    )
    with Interleaver(park_timeout_s=60.0) as il:
        for point in ("admission.build", "superstep.dispatch",
                      "superstep.fetch", "packed.pump"):
            il.hold(point)
        il.auto(seed, quantum_s=0.005)
        eng = Engine(config)
        jobs = [
            eng.submit(spec, leet, words, digests) for _ in range(2)
        ]
        for job in jobs:
            assert job.wait(timeout=120), f"seed {seed}: job wedged"
        eng.close()
        assert il.timeouts == [], f"seed {seed}: orphaned parks"
    for job in jobs:
        assert job.state == "done"
        assert hit_tuples(job.result_value) == want, (
            f"seed {seed}: stream diverged under schedule"
        )


def test_seeded_schedule_byte_parity():
    """One seed in the default tier (the sweep is slow-marked): the
    governor-chosen interleaving must not change any tenant's hits."""
    _seeded_schedule_run(0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(1, 9))
def test_seeded_schedule_sweep(seed):
    _seeded_schedule_run(seed)

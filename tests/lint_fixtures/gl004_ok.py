# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL004 must pass: numpy on static constants inside a kernel is fine
(the repo's precompute idiom); jnp handles the traced values."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def rotate(x):
    """uint32 [N, 16] -> uint32 [N, 16]."""
    perm = np.array([0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15])
    return jnp.take(x, jnp.asarray(perm), axis=1)

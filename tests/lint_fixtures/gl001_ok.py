# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL001 must pass: uint32-sized literals, plus a suppressed wide mask."""

#: 64-bit length mask, deliberately wide (host-side message-length math).
LEN_MASK = 0xFFFFFFFFFFFFFFFF  # graftlint: disable=GL001


def mix(x):
    """uint32 [N] lane mix."""
    return (x ^ 0xDEADBEEF) + 0xFFFFFFFF

# Broken twin: the fleet requeue-worker deadlock shape (PERF.md §25),
# distilled.  The reader thread's death handler re-dispatches on the
# reader itself; request() then blocks on the reply queue that only
# the reader produces — a wait-for self-cycle, not a timing bug.
import queue
import threading


class Link:
    def __init__(self):
        self._ctl_lock = threading.Lock()
        self._reply = queue.Queue()
        threading.Thread(target=self._reader, daemon=True).start()

    def request(self, doc):
        with self._ctl_lock:
            self._send(doc)
            return self._reply.get()  # blocks for the reply...

    def _send(self, doc):
        pass

    def _reader(self):
        for ev in self._events():
            if ev == "reply":
                # ...which only THIS thread ever delivers.
                self._reply.put(ev)
            else:
                self._on_death()

    def _events(self):
        return []

    def _on_death(self):
        # BROKEN: re-dispatching on the reader thread blocks the very
        # loop that must deliver the ack.
        self.request({"op": "submit"})

# Clean twin: the queue discipline runtime/autoscale.py actually
# ships (PERF.md §27).  The control loop paces on an Event wait (a
# timeout wait, not an unbounded self-produced get), spawns
# SYNCHRONOUSLY inside its own tick, and the only queue — operator
# scale requests — is produced by CALLER entries and merely drained
# (non-blocking) by the loop: no wait the loop itself must satisfy.
import queue
import threading


class Elastic:
    def __init__(self):
        self._lock = threading.Lock()
        self._requests = queue.Queue()
        self._stop = threading.Event()
        self._pool = []
        threading.Thread(target=self._loop, daemon=True).start()

    def request_scale(self, n):
        # Caller-side producer: the loop only drains, never waits.
        self._requests.put(n)

    def _loop(self):
        while not self._stop.wait(1.0):
            self._tick()

    def _tick(self):
        with self._lock:
            while True:
                try:
                    n = self._requests.get_nowait()
                except queue.Empty:
                    break
                self._apply(n)
            if self._need_capacity():
                self._pool.append(self._spawn_one())

    def _apply(self, n):
        pass

    def _need_capacity(self):
        return False

    def _spawn_one(self):
        return "sock"

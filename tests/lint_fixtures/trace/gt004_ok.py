# Clean twin of gt004_flag: the new op carries an explicit router
# decision — declared passthrough-safe (id-carrying, router-state-
# free), so the unknown-op fallback forwards it by contract.

ROUTER_PASSTHROUGH_OPS = frozenset({"rewind"})


class _JsonlSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "shutdown":
            return False
        if op == "submit":
            return True
        if op in ("pause", "cancel"):
            return True
        if op == "rewind":
            return True
        raise ValueError(op)


class _RouterSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "shutdown":
            return False
        if op == "submit":
            return True
        if op in ("pause", "cancel"):
            return True
        if doc.get("id") is not None:
            self._router.passthrough(doc)
            return True
        raise ValueError(op)

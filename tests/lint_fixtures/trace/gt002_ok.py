# Clean twin of gt002_flag: both paths take the locks in the same
# order (_a before _b), so the acquisition graph is acyclic.
import threading


class Teller:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.balance = 0
        threading.Thread(target=self._audit, daemon=True).start()

    def transfer(self, n):
        with self._a:
            self._credit(n)  # _a -> _b, same as the audit thread

    def _credit(self, n):
        with self._b:
            self.balance += n

    def _audit(self):
        with self._a:
            with self._b:
                pass

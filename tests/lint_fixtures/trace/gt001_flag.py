# Broken twin: `total` is written from both the worker thread entry
# and the caller with no guard — the shape GT001 exists to catch.
import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self._thread = threading.Thread(
            target=self._worker, daemon=True
        )
        self._thread.start()

    def _worker(self):
        self.total += 1  # unguarded shared write

    def bump(self, n):
        self.total += n  # unguarded shared write (caller side)

# Clean twin of gt003_flag: the queue-handoff discipline the real
# FleetRouter ships — the reader never dispatches; death work rides a
# bounded queue to a DEDICATED requeue worker, so the reader stays
# free to deliver the ack the dispatch waits on.
import queue
import threading


class Link:
    def __init__(self):
        self._ctl_lock = threading.Lock()
        self._reply = queue.Queue()
        self._requeue = queue.Queue()
        threading.Thread(target=self._reader, daemon=True).start()
        threading.Thread(
            target=self._requeue_worker, daemon=True
        ).start()

    def request(self, doc):
        with self._ctl_lock:
            self._send(doc)
            return self._reply.get()

    def _send(self, doc):
        pass

    def _reader(self):
        for ev in self._events():
            if ev == "reply":
                self._reply.put(ev)
            else:
                self._requeue.put(ev)  # hand off, never dispatch here

    def _events(self):
        return []

    def _requeue_worker(self):
        while True:
            item = self._requeue.get()
            if item is None:
                return
            self.request({"op": "submit"})

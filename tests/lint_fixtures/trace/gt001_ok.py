# Clean twin of gt001_flag: every write to the shared counter holds
# the declared lock, and the handoff list is a queue (a thread-safe
# channel — calling into it is never a shared write).
import queue
import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self._inbox = queue.Queue()
        self._thread = threading.Thread(
            target=self._worker, daemon=True
        )
        self._thread.start()

    def _worker(self):
        with self._lock:
            self.total += 1
        self._inbox.put("tick")

    def bump(self, n):
        with self._lock:
            self.total += n

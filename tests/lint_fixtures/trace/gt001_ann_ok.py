# Annotation-guarded clean twin: the guard grammar silences writes a
# lexical scan cannot prove — a per-line `guard=` claim (the lock is
# held by protocol) and an attribute-level `owner=` claim on the
# __init__ declaration (single-writer by construction).
import threading


class Annotated:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.mode = "idle"  # graftrace: owner=serve
        threading.Thread(target=self._worker, daemon=True).start()

    def _worker(self):
        self.total += 1  # graftrace: guard=_lock
        self.mode = "busy"

    def bump(self, n):
        self.total += n  # graftrace: guard=_lock
        self.mode = "drain"

# Broken twin: an ABBA lock-order cycle, half of it hidden behind a
# call edge — `transfer` holds _a and calls _credit (which takes _b),
# while the audit thread takes _b then _a lexically.
import threading


class Teller:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.balance = 0
        threading.Thread(target=self._audit, daemon=True).start()

    def transfer(self, n):
        with self._a:
            self._credit(n)  # acquire-while-holding: _a -> _b

    def _credit(self, n):
        with self._b:
            self.balance += n

    def _audit(self):
        with self._b:
            with self._a:  # lexical nesting: _b -> _a  (the cycle)
                pass

# Broken twin of gt003_autoscale_ok: a naive elastic control loop
# that blocks on its own spawn-ack queue.  The loop thread both
# produces (the put after a spawn) and is the ONLY producer of
# _spawned — when _need_capacity() is False the get() can never be
# satisfied by anyone else: a wait-for self-cycle (GT003), the same
# shape as the fleet requeue-worker deadlock, one layer up.
import queue
import threading


class Elastic:
    def __init__(self):
        self._spawned = queue.Queue()
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            if self._need_capacity():
                self._spawned.put(self._spawn_one())
            sock = self._spawned.get()  # only THIS thread ever puts
            self._register(sock)

    def _need_capacity(self):
        return False

    def _spawn_one(self):
        return "sock"

    def _register(self, sock):
        pass

# Broken twin: the engine session grows a 'rewind' op but the router
# session neither handles it nor declares it passthrough-safe — the
# CONTRIBUTING router-passthrough-safe rule, violated.


class _JsonlSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "shutdown":
            return False
        if op == "submit":
            return True
        if op in ("pause", "cancel"):
            return True
        if op == "rewind":  # the new serve op
            return True
        raise ValueError(op)


class _RouterSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "shutdown":
            return False
        if op == "submit":
            return True
        if op in ("pause", "cancel"):
            return True
        if doc.get("id") is not None:
            self._router.passthrough(doc)
            return True
        raise ValueError(op)

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL013 stays quiet on the idiom: bare clock STAMPS passed as data
(the drive loop's dispatch wall-clock riding the deque), recording
through the telemetry registry/timeline — which owns the arithmetic —
and injected-clock plumbing (``self._clock()`` is not a direct
``time.*`` read)."""

import time
from collections import deque


def drive(launch, batches, timeline):
    inflight = deque()
    for batch in batches:
        # A bare stamp is DATA; the timeline does the arithmetic.
        inflight.append((time.monotonic(), launch(batch)))
        if len(inflight) > 1:
            disp_t, out = inflight.popleft()
            timeline.record_fetch(dispatched_at=disp_t, inflight=1,
                                  emitted=int(out))
    while inflight:
        disp_t, out = inflight.popleft()
        timeline.record_fetch(dispatched_at=disp_t, emitted=int(out))


class Reporter:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()

    def update(self):
        now = self._clock()  # injected clock, host plumbing
        return now

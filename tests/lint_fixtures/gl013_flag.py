# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL013 must flag: ad-hoc elapsed-time arithmetic in ``runtime/``.

Every accumulation form counts — an augmented add of a clock
difference, a plain elapsed assignment, and accumulating the raw clock
itself; the telemetry registry (runtime/telemetry.py) owns timing so
merge/report semantics live in one place (PERF.md §21).
"""

import time


def drive(launch, batches):
    waited = 0.0
    for batch in batches:
        t0 = time.monotonic()
        launch(batch)
        waited += time.monotonic() - t0  # accumulation: GL013
    return waited


def run_window(launch):
    t0 = time.perf_counter()
    launch()
    elapsed = time.perf_counter() - t0  # elapsed assignment: GL013
    return elapsed


def wall_clock_total(steps):
    total = 0.0
    for step in steps:
        step()
        total += time.time()  # raw clock accumulation: GL013
    return total

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL002 must flag: float literal and widening dtype in a traced body."""

import jax
import jax.numpy as jnp


@jax.jit
def scale(x):
    """uint32 [N] -> uint32 [N]."""
    y = x.astype(jnp.int64)
    return y * 1.5

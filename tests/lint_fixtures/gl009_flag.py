# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL009 must flag: bare print() in a library module (stdout is the
candidate byte stream)."""


def report(n):
    print(f"emitted {n} candidates")

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL006 must pass: config params marked static (or closed over)."""

from functools import partial

import jax


def run(x, algo, out_width):
    """uint32 [N] -> uint32 [N] under a config."""
    return x if algo == "md5" else x[:out_width]


fast_run = jax.jit(run, static_argnames=("algo", "out_width"))


@partial(jax.jit, static_argnames=("block_stride",))
def stepper(x, block_stride):
    """uint32 [N] -> uint32 [N]."""
    return x * block_stride


def make_step(algo):
    """The builder idiom: config closed over, data-only signature."""

    def step(x):
        return x

    return jax.jit(step)

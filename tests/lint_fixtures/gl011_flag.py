# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL011 must flag: host syncs inside lax loop bodies.

A scan/while body runs per device iteration; ``int()``/``.item()``/
``np.asarray`` on its carry forces a host round trip per step — the
exact overhead the superstep executor exists to remove.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def sweep_scan(plan, b0, steps):
    def step(carry, _):
        cursor, total = carry
        count = jnp.minimum(cursor, 128)
        total = total + int(carry[1])  # host sync on the carry
        host_view = np.asarray(carry)  # host numpy on the carry
        done = count.item()  # per-iteration scalar fetch
        return (cursor + 1, total + done + host_view.sum()), None

    return lax.scan(step, (b0, jnp.zeros((), jnp.int32)), None,
                    length=steps)


def sweep_lambda(xs):
    # Inline lambda bodies are loop bodies too.
    return lax.scan(lambda c, x: (c + int(c), None), jnp.int32(0), xs)


def sweep_while(limit):
    def cond(carry):
        return carry[0] < limit

    def body(carry):
        cursor, total = carry
        total = total + int(carry[0])  # host sync on the carry
        return (cursor + 1, total)

    # Keyword-style call (jax's own signature names) resolves too.
    return lax.while_loop(cond_fun=cond, body_fun=body,
                          init_val=(jnp.int32(0), jnp.int32(0)))

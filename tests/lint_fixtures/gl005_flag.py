# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL005 must flag: Python loops driven by traced arguments — iterating
a tracer, a range() over a traced scalar, and a while on a traced
condition."""

import jax


@jax.jit
def fold(words, n):
    """uint32 [N] -> uint32 scalar."""
    acc = 0
    for w in words:
        acc = acc ^ w
    for i in range(n):
        acc = acc + i
    while n > acc:
        acc = acc + 1
    return acc

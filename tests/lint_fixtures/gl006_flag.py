# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL006 must flag: jitting config-like params as traced arguments."""

import jax


def run(x, algo, out_width):
    """uint32 [N] -> uint32 [N] under a config."""
    return x if algo == "md5" else x[:out_width]


fast_run = jax.jit(run)


@jax.jit
def stepper(x, block_stride):
    """uint32 [N] -> uint32 [N]."""
    return x * block_stride

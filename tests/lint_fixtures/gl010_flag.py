# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL010 must flag: mutable defaults shared across calls."""


def collect(hit, acc=[]):
    acc.append(hit)
    return acc


def configure(overrides={}, *, tags=set()):
    return overrides, tags

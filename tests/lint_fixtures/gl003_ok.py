# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL003 must pass: host wrapper concretizes AFTER the jitted body."""

import jax
import jax.numpy as jnp


@jax.jit
def count_hits(hits):
    """bool [N] -> int32 scalar (on device)."""
    return jnp.sum(hits.astype(jnp.int32))


def fetch_count(hits):
    """Host wrapper: device scalar -> Python int (outside the trace)."""
    return int(count_hits(hits).item())

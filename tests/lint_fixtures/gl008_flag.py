# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL008 must flag: public ops without a shape/dtype contract."""


def expand(tokens, lengths):
    return tokens


def pack(rows):
    """Pack the rows for launch."""
    return rows

"""Telemetry-placement fixture (PERF.md §21): registry/timeline calls
must stay off the hot path.

``broken_drive_inflight`` records a span inside the dispatch fill loop
— host work inserted into the in-flight window narrows the pipeline
overlap (PERF.md §18) without failing a parity test.  ``broken_scan``
calls the registry from a ``lax.scan`` body handed to ``jit`` — at
best it records once at trace time (lying metrics), at worst it
smuggles a per-step host round trip into the compiled program.  The
clean twins show the sanctioned shape: dispatch wall-clocks ride the
deque as plain data, and the ONE telemetry call lands at the consumed
fetch boundary.

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np


def clean_drive(call, make_bufs, total, advance, depth, timeline):
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            # A bare monotonic stamp is DATA, not a telemetry call.
            inflight.append((b0, time.monotonic(), call(b0, free.pop())))
            b0 += advance
        sb0, disp_t, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        # The sanctioned placement: the consumed fetch boundary.
        timeline.record_fetch(dispatched_at=disp_t,
                              inflight=len(inflight), emitted=ne, hits=nh)
        done += ne
    return done


def broken_drive_inflight(call, make_bufs, total, advance, depth,
                          timeline):
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            # SIN: a span record per DISPATCH sits in the in-flight
            # window — host work between dispatches eats the overlap.
            timeline.record_fetch(kind="dispatch", index=b0)
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def clean_scan(jit, scan, telemetry, xs):
    def body(carry, x):
        return carry + x, x

    def step(xs_):
        return scan(body, 0, xs_)

    total, _ys = jit(step)(xs)
    # Post-fetch, host-side: the sanctioned placement.
    telemetry.counter("scan.total").add(int(total))
    return total


def broken_scan(jit, scan, telemetry, xs):
    def body(carry, x):
        # SIN: a registry call inside the scan body — trace-time at
        # best, a smuggled per-step host round trip at worst.
        telemetry.counter("scan.steps").add(1)
        return carry + x, x

    def step(xs_):
        return scan(body, 0, xs_)

    total, _ys = jit(step)(xs)
    return total

"""Serve-round discipline fixtures: the resident engine's multiplexing
loop (PERF.md §20).

``clean_round`` is the sanctioned shape: one ``next()`` tick per
runnable job per round, control handled at the same boundaries, no
device→host fetch anywhere — the machines own the per-superstep
barrier.  The ``broken_*`` variants commit the three serve-loop sins:
draining one job to completion inside the round (monopolization — the
other tenants starve), double-ticking every job (one tenant's boundary
latency doubles everyone's), and fetching device data in the scheduler
(barriers every tenant behind one job's in-flight superstep).

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

import numpy as np


def clean_round(slots, finish, fail):
    for slot in slots:
        if slot.cancelled:
            finish(slot, None)
            continue
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)
        except Exception as exc:  # noqa: BLE001 — job-scoped failure
            fail(slot, exc)


def broken_drain_round(slots, finish, fail):
    """Monopolization: the first job runs to completion while every
    other tenant waits — the whole point of interleaving at superstep
    boundaries is gone."""
    for slot in slots:
        while True:
            try:
                next(slot.machine)
            except StopIteration as done:
                finish(slot, done.value)
                break


def broken_guarded_drain_round(slots, finish, fail):
    """The monopolization regression hidden behind a guard: the drain
    loop sits under an ``if``/``try`` — it still drains one tenant to
    completion while the rest starve."""
    for slot in slots:
        if not slot.cancelled:
            try:
                while True:
                    next(slot.machine)
            except StopIteration as done:
                finish(slot, done.value)


def broken_condition_drain_round(slots, finish, fail):
    """Monopolization written as a loop CONDITION: the tick in the
    while test runs per iteration — the drain, spelled differently."""
    for slot in slots:
        while next(slot.machine, None) is not None:
            pass
        finish(slot, None)


def broken_double_tick_round(slots, finish, fail):
    """Two boundary ticks per job per round: a half-fair drain — one
    tenant's superstep latency is now two of everyone else's."""
    for slot in slots:
        try:
            next(slot.machine)
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)


def broken_fetch_round(slots, finish, fail):
    """A device→host fetch in the scheduler: coercing one job's device
    counters barriers EVERY tenant behind that job's in-flight work."""
    for slot in slots:
        if int(np.asarray(slot.out["counters"])[0]) > 0:
            finish(slot, None)
            continue
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)


def broken_sync_round(slots, finish, fail):
    """The same barrier spelled explicitly."""
    for slot in slots:
        slot.out["counters"].block_until_ready()
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)


# ---------------------------------------------------------------------------
# Packed-round fixtures (audit_pack_round, PERF.md §22): the fused
# group's dispatch/fetch/split loop.  ``clean_packed_round`` is the
# sanctioned shape — one dispatch site in the dispatch-ahead fill
# while, ONE unconditional counters fetch, hit slice behind the
# hit-count guard, per-member split as pure host bookkeeping.  The
# broken variants commit the packed sins: dispatching per member
# (the per-job-dispatch regression — the fused round degraded back to
# N round trips), and a fetch hidden in the segment bookkeeping
# (barriers the round once per member).
# ---------------------------------------------------------------------------


def clean_packed_round(self):
    while self.work_remains() and len(self.inflight) < self.depth:
        snap = self.b0.copy()
        self.inflight.append((snap, 0.0, self._call(snap, self.free.pop())))
        self.b0 = self.b0 + self.adv
    if not self.inflight:
        return False
    snap, disp_t, out = self.inflight.popleft()
    counters = np.asarray(out["counters"])
    if int(counters[1].sum()):
        dev_hits = np.asarray(out["dev_hits"])
        if int(dev_hits.max()) <= self.cap:
            hw = np.asarray(out["hit_word"])
            self.split(hw, dev_hits)
    ne_rows = counters[0].tolist()
    for j, member in enumerate(self.members):
        member.push(ne_rows[j], disp_t)
    return True


def broken_packed_perjob_dispatch(self):
    """The per-job-dispatch regression: one device dispatch PER MEMBER
    inside the split loop — the packed round quietly degraded back to N
    round trips per round."""
    for j, member in enumerate(self.members):
        out = self._call(member.b0, self.free.pop())
        self.inflight.append((member.b0, 0.0, out))
    snap, disp_t, out = self.inflight.popleft()
    counters = np.asarray(out["counters"])
    for j, member in enumerate(self.members):
        member.push(int(counters[0, j]), disp_t)
    return True


def broken_packed_segment_fetch(self):
    """A fetch hidden in the segment bookkeeping: each member's counter
    column is coerced from the DEVICE result inside the split loop —
    one barrier per member instead of one per round."""
    while self.work_remains() and len(self.inflight) < self.depth:
        snap = self.b0.copy()
        self.inflight.append((snap, 0.0, self._call(snap, self.free.pop())))
        self.b0 = self.b0 + self.adv
    snap, disp_t, out = self.inflight.popleft()
    for j, member in enumerate(self.members):
        member.push(int(np.asarray(out["counters"])[0, j]), disp_t)
    return True


def broken_packed_double_fetch(self):
    """Two unconditional fetches per round: the counters AND the hit
    buffers, hit-bearing or not — the §18 double-fetch regression in
    packed clothing."""
    while self.work_remains() and len(self.inflight) < self.depth:
        snap = self.b0.copy()
        self.inflight.append((snap, 0.0, self._call(snap, self.free.pop())))
        self.b0 = self.b0 + self.adv
    snap, disp_t, out = self.inflight.popleft()
    counters = np.asarray(out["counters"])
    hw = np.asarray(out["hit_word"])
    ne_rows = counters[0].tolist()
    for j, member in enumerate(self.members):
        member.push(ne_rows[j], disp_t)
    self.split(hw)
    return True

"""Serve-round discipline fixtures: the resident engine's multiplexing
loop (PERF.md §20).

``clean_round`` is the sanctioned shape: one ``next()`` tick per
runnable job per round, control handled at the same boundaries, no
device→host fetch anywhere — the machines own the per-superstep
barrier.  The ``broken_*`` variants commit the three serve-loop sins:
draining one job to completion inside the round (monopolization — the
other tenants starve), double-ticking every job (one tenant's boundary
latency doubles everyone's), and fetching device data in the scheduler
(barriers every tenant behind one job's in-flight superstep).

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

import numpy as np


def clean_round(slots, finish, fail):
    for slot in slots:
        if slot.cancelled:
            finish(slot, None)
            continue
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)
        except Exception as exc:  # noqa: BLE001 — job-scoped failure
            fail(slot, exc)


def broken_drain_round(slots, finish, fail):
    """Monopolization: the first job runs to completion while every
    other tenant waits — the whole point of interleaving at superstep
    boundaries is gone."""
    for slot in slots:
        while True:
            try:
                next(slot.machine)
            except StopIteration as done:
                finish(slot, done.value)
                break


def broken_guarded_drain_round(slots, finish, fail):
    """The monopolization regression hidden behind a guard: the drain
    loop sits under an ``if``/``try`` — it still drains one tenant to
    completion while the rest starve."""
    for slot in slots:
        if not slot.cancelled:
            try:
                while True:
                    next(slot.machine)
            except StopIteration as done:
                finish(slot, done.value)


def broken_condition_drain_round(slots, finish, fail):
    """Monopolization written as a loop CONDITION: the tick in the
    while test runs per iteration — the drain, spelled differently."""
    for slot in slots:
        while next(slot.machine, None) is not None:
            pass
        finish(slot, None)


def broken_double_tick_round(slots, finish, fail):
    """Two boundary ticks per job per round: a half-fair drain — one
    tenant's superstep latency is now two of everyone else's."""
    for slot in slots:
        try:
            next(slot.machine)
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)


def broken_fetch_round(slots, finish, fail):
    """A device→host fetch in the scheduler: coercing one job's device
    counters barriers EVERY tenant behind that job's in-flight work."""
    for slot in slots:
        if int(np.asarray(slot.out["counters"])[0]) > 0:
            finish(slot, None)
            continue
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)


def broken_sync_round(slots, finish, fail):
    """The same barrier spelled explicitly."""
    for slot in slots:
        slot.out["counters"].block_until_ready()
        try:
            next(slot.machine)
        except StopIteration as done:
            finish(slot, done.value)

"""Pallas bounds fixture: a store past the BlockSpec block extent.

``broken_launch``'s kernel writes row 4 of a 4-row output block —
statically out of bounds, and Pallas does NOT validate static integer
indices at trace time (only ``pl.dslice`` forms are checked), so on
chip this clobbers a VMEM neighbor.  ``clean_launch`` writes the last
valid row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK = (4, 4, 8)


def _launch(kernel, x):
    return pl.pallas_call(
        kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec(_BLOCK, lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec(_BLOCK, lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 4, 8), jnp.int32),
        interpret=True,
    )(x)


def example_args():
    return (jnp.zeros((8, 4, 8), jnp.int32),)


def clean_launch(x):
    def kernel(x_ref, o_ref):
        o_ref[:, 3, :] = x_ref[:, 0, :] + 1  # last valid row

    return _launch(kernel, x)


def broken_launch(x):
    def kernel(x_ref, o_ref):
        o_ref[:, 4, :] = x_ref[:, 0, :] + 1  # one past the block extent

    return _launch(kernel, x)


def broken_launch_dslice(x):
    """A traced-CONSTANT dslice start: NDIndexer cannot validate it at
    trace time (unlike a plain-int ``pl.dslice``, which raises), so the
    start arrives in the kernel jaxpr as a Literal holding a 0-d array —
    the audit must still resolve it and flag rows [2, 6) > 4."""

    def kernel(x_ref, o_ref):
        pl.store(
            o_ref,
            (slice(None), pl.dslice(jnp.int32(2), 3), slice(None)),
            jnp.broadcast_to(x_ref[:, 0, :][:, None, :], (4, 3, 8)) + 1,
        )

    return _launch(kernel, x)

"""Dead-stage fixture: the PERF.md §15 membership-DCE reproduction.

``broken_body`` is the exact trap shape PR 3 found in the kernel bench:
the loop computes hash + membership but ACCUMULATES ONLY ``n_emitted``,
so XLA dead-code-eliminates the digest-membership stage (and the hash
feeding it) from the optimized module — while every parity test stays
green, because parity tests consume hits.  ``clean_body`` keeps the
hits live (the production crack-step contract).

Both route through the real ``ops.hashes.md5`` / ``ops.digest_member``
so the audit's source-metadata stage markers apply.
"""

from __future__ import annotations

import jax.numpy as jnp

from hashcat_a5_table_generator_tpu.ops.hashes import md5
from hashcat_a5_table_generator_tpu.ops.membership import (
    build_digest_set,
    digest_member,
)

#: Checked stages: this fixture has no expand stage by construction.
STAGES = ("hash", "membership")


def example_args():
    ds = build_digest_set([bytes(16), bytes(range(16))], "md5")
    msgs = jnp.zeros((256, 16), jnp.uint8)
    lens = jnp.full((256,), 8, jnp.int32)
    return (
        msgs, lens, jnp.asarray(ds.rows), jnp.asarray(ds.bitmap),
    )


def clean_body(msgs, lens, rows, bitmap):
    """Hash + membership with the hit count LIVE (honest contract)."""
    emit = lens > 0
    state = md5(msgs, lens)
    hit = digest_member(state, rows, bitmap) & emit
    return {
        "n_emitted": jnp.sum(emit.astype(jnp.int32)),
        "n_hits": jnp.sum(hit.astype(jnp.int32)),
    }


def broken_body(msgs, lens, rows, bitmap):
    """The §15 trap: hash + membership traced, but only ``n_emitted``
    escapes — XLA drops both stages from the optimized module."""
    emit = lens > 0
    state = md5(msgs, lens)
    hit = digest_member(state, rows, bitmap) & emit
    del hit  # emitted-only accumulator: the membership consumer is gone
    return {"n_emitted": jnp.sum(emit.astype(jnp.int32))}

"""Float-purity fixture: a hash-like reduction that leaks through f32.

``broken_stage`` is the classic accident: ``jnp.mean`` (or a true ``/``)
promotes uint32 lanes to float32, silently losing bits above 2^24 —
digests are exact or worthless.  ``clean_stage`` is the integer idiom.
"""

from __future__ import annotations

import jax.numpy as jnp


def example_args():
    return (jnp.zeros((128, 4), jnp.uint32),)


def clean_stage(state):
    """Pure integer mixing (the repo's real kernels look like this)."""
    acc = state[:, 0] ^ (state[:, 1] << jnp.uint32(7))
    acc = acc + state[:, 2] * jnp.uint32(0x9E3779B9)
    return acc ^ state[:, 3]


def broken_stage(state):
    """A float round trip in the middle of uint32 arithmetic."""
    centered = state - jnp.mean(state, axis=0)  # promotes to float!
    return centered.astype(jnp.uint32)[:, 0]

"""graftaudit fixture corpus: one deliberately-broken kernel/body per
semantic check, each with a clean twin (tests/test_graftaudit.py).

Unlike the graftlint corpus (source snippets linted under virtual
paths), these are REAL traceable jax programs — the audit operates on
jaxprs and optimized HLO, so the fixtures must actually trace/compile.
Every fixture is tiny (hundreds of lanes, one or two grid steps): the
whole corpus traces in seconds on the CPU backend.
"""

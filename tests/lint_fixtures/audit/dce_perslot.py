"""Dead-stage fixture pair for the PER-SLOT emission body (PERF.md §17).

The §15 membership-DCE trap, re-armed against the new splice: the
per-slot piece path rebuilt the expand stage around host-precomputed
group tables, so this pair proves (a) the production crack-step contract
still keeps expand+hash+membership alive through the piece splice, and
(b) an emitted-only accumulator over the SAME piece body still lets XLA
drop the membership stage — i.e. the audit's stage markers keep working
on the rewritten body, not just the legacy one ``dce_membership.py``
pins.
"""

from __future__ import annotations

import jax.numpy as jnp

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    digest_arrays,
    make_fused_body,
    piece_arrays,
    plan_arrays,
    table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import (
    pack_words,
    piece_schema_for,
)
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

#: All three crack stages must survive in the clean body.
STAGES = ("expand", "hash", "membership")

_NB, _STRIDE = 8, 128


def _setup():
    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table({b"a": [b"X"], b"e": [b"3"], b"o": [b"0"]})
    plan = build_plan(spec, ct, pack_words([b"paooaeoale", b"aeaeae"]))
    pieces = piece_schema_for(plan, ct)
    assert pieces is not None, "fixture plan must be piece-eligible"
    batch, _, _ = make_blocks(
        plan, start_word=0, start_rank=0, max_variants=_NB * _STRIDE,
        max_blocks=_NB, fixed_stride=_STRIDE,
    )
    p = plan_arrays(plan)
    p.update(piece_arrays(pieces))
    ds = build_digest_set([bytes(16), bytes(range(16))], "md5")
    return spec, plan, pieces, p, table_arrays(ct), digest_arrays(ds), \
        block_arrays(pad_batch(batch, _NB), num_blocks=_NB)


def example_args():
    _, _, _, p, t, d, b = _setup()
    return (p, t, d, b)


def _body():
    spec, plan, pieces, *_ = _setup()
    return make_fused_body(
        spec, num_lanes=_NB * _STRIDE, out_width=int(plan.out_width),
        block_stride=_STRIDE, radix2=True, pieces=pieces,
    )


def clean_body(p, t, d, b):
    """The production crack-step contract over the piece splice: hits
    stay live, so all three stages must survive optimization."""
    return _body()(p, t, d, b)


def broken_body(p, t, d, b):
    """The §15 trap shape over the piece splice: only ``n_emitted``
    escapes, so XLA drops membership (and the hash feeding it)."""
    out = _body()(p, t, d, b)
    return {"n_emitted": out["n_emitted"]}


def __graftlint_skip__():  # pragma: no cover - marker only
    """Fixture corpus: excluded from repo-wide lint sweeps."""

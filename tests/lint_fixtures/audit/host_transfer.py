"""Host-transfer fixture: a callback smuggled into a scan body.

``broken_sweep`` plants ``jax.debug.print`` inside the ``lax.scan``
step — a device→host round trip PER STEP, which silently turns the
superstep executor's one-fetch-per-superstep contract into S hidden
syncs.  ``clean_sweep`` is the same loop without the callback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def example_args():
    return (jnp.arange(64, dtype=jnp.int32),)


def clean_sweep(xs):
    def step(carry, x):
        carry = carry + jnp.sum(x)
        return carry, None

    total, _ = jax.lax.scan(step, jnp.int32(0), xs.reshape(8, 8))
    return total


def broken_sweep(xs):
    def step(carry, x):
        carry = carry + jnp.sum(x)
        jax.debug.print("step total {t}", t=carry)  # host sync per step!
        return carry, None

    total, _ = jax.lax.scan(step, jnp.int32(0), xs.reshape(8, 8))
    return total

"""Split-merge discipline fixtures: the router's k-way shard-hit merge
(PERF.md §31).

``CleanMerge`` is the sanctioned shape — the production
``_SplitMerge`` reduced to its audited skeleton: one unconditional
wire decode per merge round (the rank string parses once, at ingress),
a lock-held append into the shard's buffer, and a drain whose per-
shard bookkeeping compares already-parsed keys and pops every
releasable head before the lock drops.  The ``Broken*`` variants
commit the three merge-loop sins: re-decoding the event inside the
per-shard drain scan (per-shard parse work once per hit), a second
unconditional decode at ingress (per-hit work duplicated across the
whole merged stream), and appending into a buffer nothing in the
class ever pops (unbounded hoarding — one stalled shard holds every
sibling's hits for the rest of the job).

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

from collections import deque


class CleanMerge:
    def __init__(self, n):
        self.n = n
        self._bufs = [deque() for _ in range(n)]
        self._marks = [None] * n
        self._out = []

    def _merge_round(self, i, ev):
        key = (ev["word_index"], int(ev["rank"]))
        with self._lock:
            self._bufs[i].append((key, ev))
            self._marks[i] = key
            self._drain()

    def _drain(self):
        while True:
            best, src = None, -1
            for k in range(self.n):
                if self._bufs[k] and (
                    best is None or self._bufs[k][0][0] < best
                ):
                    best, src = self._bufs[k][0][0], k
            if best is None:
                return
            self._out.append(self._bufs[src].popleft()[1])

    def _flush(self):
        self._out.clear()


class BrokenPerShardDecode(CleanMerge):
    """The per-shard-parse regression: the drain scan re-decodes the
    buffered events' rank strings once per shard per hit instead of
    comparing the parsed keys stored at ingress."""

    def _merge_round(self, i, ev):
        key = (ev["word_index"], int(ev["rank"]))
        with self._lock:
            self._bufs[i].append((key, ev))
            self._marks[i] = key
            best, src = None, -1
            for k in range(self.n):
                if self._bufs[k]:
                    head = int(self._bufs[k][0][1]["rank"])
                    if best is None or head < best:
                        best, src = head, k
            if src >= 0:
                self._out.append(self._bufs[src].popleft()[1])


class BrokenDoubleDecode(CleanMerge):
    """A second unconditional decode of the same wire event — per-hit
    work duplicated across the whole merged stream."""

    def _merge_round(self, i, ev):
        key = (int(ev["word_index"]), int(ev["rank"]))
        with self._lock:
            self._bufs[i].append((key, ev))
            self._marks[i] = key
            self._drain()


class BrokenHoard:
    """Append-only buffering: nothing in the class ever pops or clears
    ``_hoard`` — one stalled sibling makes the buffer grow with the
    whole merged stream."""

    def __init__(self, n):
        self.n = n
        self._hoard = deque()

    def _merge_round(self, i, ev):
        key = (ev["word_index"], int(ev["rank"]))
        with self._lock:
            self._hoard.append((key, ev))

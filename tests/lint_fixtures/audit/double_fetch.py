"""Drive-loop fetch-discipline fixture: the double-fetch regression.

``broken_drive`` commits the two pipeline-killing sins the
``drive-fetch`` audit exists for (PERF.md §18): it coerces the counters
of the superstep it JUST dispatched (a completion barrier on the
in-flight buffer set — the overlap is gone) and it fetches the popped
superstep's result twice unconditionally (the second fetch re-barriers
what the stacked-counters contract made one round trip).
``clean_drive`` is the sanctioned shape: one unconditional fetch of the
popped result, hit buffers only behind the hit-count guard.

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

from collections import deque

import numpy as np


def clean_drive(call, make_bufs, total, advance, depth, process_hits):
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        if nh:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def clean_drive_bound_counters(call, make_bufs, total, advance, depth,
                               process_hits):
    """Sanctioned shape, counters BOUND first: the ``np.asarray`` is the
    one round trip; ``int(counters[i])`` is host arithmetic on the
    already-materialized array, not a second fetch."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        counters = np.asarray(out["counters"])
        ne = int(counters[0])
        nh = int(counters[1])
        if nh:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def clean_drive_annotated(call, make_bufs, total, advance, depth,
                          process_hits, annotate):
    """Sanctioned shape under a profiler annotation: the ``with`` block
    does not gate its body, but the hit guard nested inside it still
    does — the guarded hit-slice fetch must stay conditional."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        with annotate("a5.consume_superstep"):
            ne, nh = (int(x) for x in np.asarray(out["counters"]))
            if nh:
                dev_hits = np.asarray(out["dev_hits"])
                process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def broken_drive_unbound(call, make_bufs, total, advance, depth,
                         process_hits):
    """Sin 1 in the production dispatch shape: the call result is never
    bound to a name — it goes straight into the deque — and the barrier
    fetch reaches the in-flight superstep THROUGH the container."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        # Fetching through the deque barriers the JUST-dispatched
        # superstep exactly like naming it first would.
        done += int(inflight[-1][1]["n_emitted"])
        sb0, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        if nh:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def broken_drive_guard_fetch(call, make_bufs, total, advance, depth,
                             process_hits):
    """Sin 2 hidden in a CONDITION: the second unconditional fetch is
    written as the hit guard's test — it still runs every superstep."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        if int(out["n_hits"]):
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def clean_drive_inline_coercion(call, make_bufs, total, advance, depth,
                                process_hits):
    """Sanctioned shape spelled INLINE: ``int(np.asarray(...)[0])`` is
    one round trip — the inner ``asarray`` is the fetch, the outer
    ``int`` is host arithmetic on its materialized result."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        ne = int(np.asarray(out["counters"])[0])
        if ne:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def broken_drive_loop_fetch(call, make_bufs, total, advance, depth,
                            process_hits):
    """The double-fetch regression written as a LOOP: a single ``int()``
    call node in a per-key loop is two device round trips per
    superstep."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        totals = {}
        for key in ("n_emitted", "n_hits"):
            totals[key] = int(out[key])
        if totals["n_hits"]:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += totals["n_emitted"]
    return done


def broken_drive(call, make_bufs, total, advance, depth, process_hits):
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            fresh = call(b0, free.pop())
            # Sin 1: fetching the just-dispatched superstep's counters
            # barriers the in-flight buffer set — no overlap remains.
            done += int(fresh["n_emitted"])
            inflight.append((b0, fresh))
            b0 += advance
        sb0, out = inflight.popleft()
        ne, nh = (int(x) for x in np.asarray(out["counters"]))
        # Sin 2: a SECOND unconditional fetch of the popped result — the
        # double-fetch regression (two round trips per superstep).
        nh = int(out["n_hits"])
        if nh:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def clean_drive_recovering(call, make_bufs, total, advance, depth,
                           process_hits, recover):
    """Sanctioned shape under the fault-supervision try (PERF.md §23):
    the dispatch fill loop and the one counters fetch sit in a try
    whose handler only does host-side recovery bookkeeping — still
    exactly one unconditional fetch of the popped result."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    consumed = 0
    done = 0
    while b0 < total or inflight:
        try:
            while b0 < total and len(inflight) < depth:
                inflight.append((b0, call(b0, free.pop())))
                b0 += advance
            sb0, out = inflight.popleft()
            counters = np.asarray(out["counters"])
        except Exception:
            recover()
            inflight.clear()
            free[:] = [make_bufs() for _ in range(depth)]
            b0 = consumed
            continue
        consumed = sb0 + advance
        ne = int(counters[0])
        nh = int(counters[1])
        if nh:
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done


def broken_drive_recovering_inflight_fetch(call, make_bufs, total,
                                           advance, depth, process_hits,
                                           recover):
    """The in-flight fetch sin HIDDEN by the recovery try: the fill
    loop now nests inside a Try, and the audit must still track its
    dispatches as in-flight — fetching through the deque barriers the
    pipeline exactly as it did pre-§23."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        try:
            while b0 < total and len(inflight) < depth:
                inflight.append((b0, call(b0, free.pop())))
                b0 += advance
            done += int(inflight[-1][1]["n_emitted"])  # in-flight fetch!
            sb0, out = inflight.popleft()
            counters = np.asarray(out["counters"])
        except Exception:
            recover()
            continue
        ne = int(counters[0])
        if int(counters[1]):
            dev_hits = np.asarray(out["dev_hits"])
            process_hits(sb0, dev_hits)
        free.append({"hit_word": out["hit_word"],
                     "hit_rank": out["hit_rank"]})
        done += ne
    return done

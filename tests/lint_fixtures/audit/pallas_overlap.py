"""Pallas race fixture: two grid steps writing the same output block.

``broken_launch`` pins the OUTPUT index_map to block 0 while the grid
has two steps — on TPU the sequential grid makes step 1 silently
overwrite step 0 (and interpret mode happens to agree), which is a
race/correctness bug whenever the revisit is unintended; no kernel in
this repo accumulates across grid steps.  ``clean_launch`` maps each
grid step to its own block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK = (4, 8)


def _launch(out_index_map, x):
    def kernel(x_ref, o_ref):
        o_ref[:, :] = x_ref[:, :] * 2

    return pl.pallas_call(
        kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec(_BLOCK, lambda i: (i, 0))],
        out_specs=pl.BlockSpec(_BLOCK, out_index_map),
        out_shape=jax.ShapeDtypeStruct((8, 8), jnp.int32),
        interpret=True,
    )(x)


def example_args():
    return (jnp.zeros((8, 8), jnp.int32),)


def clean_launch(x):
    return _launch(lambda i: (i, 0), x)


def broken_launch(x):
    return _launch(lambda i: (0, 0), x)  # every step writes block 0

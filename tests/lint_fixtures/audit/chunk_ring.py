"""Chunk-ring consume-discipline fixtures (PERF.md §19).

The streaming drive pops compiled chunks off the worker ring, sweeps
each one, and releases it before the ring advances — that loop's shape
IS the bounded-memory and compile-overlap contract.  ``clean_ring`` is
the sanctioned form; the ``broken_*`` variants each commit one of the
regressions ``audit_chunk_ring`` exists to catch: a synchronous
transfer/compile inside the consume loop (serializes host work the ring
overlaps), a materialized ring (every chunk resident at once), a
conditional or missing release (chunks leak past the ring bound), and a
chunk hoarded into a container (the same leak spelled differently).

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

import numpy as np


def clean_ring(compiler, drive_chunk):
    for chunk in compiler:
        drive_chunk(chunk)
        chunk.release()


def broken_ring_transfer(compiler, drive_chunk, jnp):
    """Sin 1: a host→device transfer in the consume loop — the chunk's
    arrays were supposed to be prefetched by the worker; re-shipping
    them here barriers the sweep behind the transfer."""
    for chunk in compiler:
        tables = jnp.asarray(chunk.plan.tokens)
        drive_chunk(chunk, tables)
        chunk.release()


def broken_ring_compile(compiler, drive_chunk, spec, ct, packed):
    """Sin 1 spelled as a compile: building the plan in the consume
    loop re-serializes the exact host work the ring's worker thread
    exists to overlap."""
    for chunk in compiler:
        plan = build_plan(spec, ct, packed)  # noqa: F821 — AST fixture
        drive_chunk(chunk, plan)
        chunk.release()


def broken_ring_materialized(compiler, drive_chunk):
    """Sin 2: materializing the ring — every chunk compiled and resident
    before the first sweep, O(dictionary) memory again."""
    for chunk in list(compiler):
        drive_chunk(chunk)
        chunk.release()


def broken_ring_conditional_release(compiler, drive_chunk):
    """Sin 3: a conditional release — error paths (or hit-bearing
    chunks, or whatever the guard keys on) leak their arrays past the
    ring bound."""
    for chunk in compiler:
        ok = drive_chunk(chunk)
        if ok:
            chunk.release()


def broken_ring_no_release(compiler, drive_chunk):
    """Sin 3, fully absent: nothing ever frees the consumed chunk."""
    done = 0
    for chunk in compiler:
        done += int(np.int64(drive_chunk(chunk)))
    return done


def broken_ring_hoard(compiler, drive_chunk):
    """Sin 4: consumed chunks collected into a list — released or not,
    the container keeps them alive."""
    swept = []
    for chunk in compiler:
        drive_chunk(chunk)
        swept.append(chunk)
        chunk.release()
    return swept

"""Fault-hook guard fixture (PERF.md §23): the injection seams in the
drive/pump loops must keep the no-op-guarded shape —

    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("point")

``audit_fault_hooks`` must FIRE on a bare always-on hook (rule matching
runs in the dispatch fill window on every arrival) and on a hook behind
the WRONG guard, and stay quiet on the sanctioned shape — including a
hook whose guard sits above a try block, the fault-supervised drive's
real layout.

AST-only fixtures: the audit reads source, nothing here ever runs.
"""

from __future__ import annotations

from collections import deque


def clean_drive_hooked(call, make_bufs, total, advance, depth, faults):
    """The sanctioned shape: every fire() behind ACTIVE is not None."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            if faults.ACTIVE is not None:
                faults.ACTIVE.fire("superstep.dispatch")
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("superstep.fetch")
        done += consume(sb0, out)  # noqa: F821 — fixture stub
        free.append(out)
    return done


def clean_drive_hooked_recovering(call, make_bufs, total, advance, depth,
                                  faults, recover):
    """Sanctioned shape under the fault-supervision try: the guard
    stays immediately around each fire(), with the try wrapping the
    whole dispatch/fetch half (the production drive's layout)."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        try:
            while b0 < total and len(inflight) < depth:
                if faults.ACTIVE is not None:
                    faults.ACTIVE.fire("superstep.dispatch")
                inflight.append((b0, call(b0, free.pop())))
                b0 += advance
            sb0, out = inflight.popleft()
        except Exception:
            b0 = recover(inflight, free)
            continue
        done += consume(sb0, out)  # noqa: F821 — fixture stub
        free.append(out)
    return done


def broken_drive_bare_hook(call, make_bufs, total, advance, depth, faults):
    """The finding: an always-on fire() in the dispatch fill window."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            faults.ACTIVE.fire("superstep.dispatch")  # no guard!
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        done += consume(sb0, out)  # noqa: F821 — fixture stub
        free.append(out)
    return done


def clean_router_dispatch_hooked(pick, request, job, faults):
    """The fleet placement seam's sanctioned shape (PERF.md §27): the
    guard sits immediately around the fire at dispatch entry."""
    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("router.place")
    link = pick(job.token)
    return request(link, job.doc)


def broken_spawn_bare_hook(spawner, attach, faults):
    """The finding, fleet-shaped: a bare fire inside the spawn try —
    rule matching would run on every scale-up arrival."""
    try:
        faults.ACTIVE.fire("engine.spawn")  # no guard!
        endpoint, eid, proc = spawner()
        attach(endpoint, eid, proc)
    except Exception:
        return False
    return True


def broken_drive_wrong_guard(call, make_bufs, total, advance, depth,
                             faults, debug):
    """A guard that is not the ACTIVE-is-not-None test does not count:
    the production no-op contract is the attribute check itself."""
    free = [make_bufs() for _ in range(depth)]
    inflight = deque()
    b0 = 0
    done = 0
    while b0 < total or inflight:
        while b0 < total and len(inflight) < depth:
            if debug:
                faults.ACTIVE.fire("superstep.dispatch")
            inflight.append((b0, call(b0, free.pop())))
            b0 += advance
        sb0, out = inflight.popleft()
        done += consume(sb0, out)  # noqa: F821 — fixture stub
        free.append(out)
    return done

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL009 must pass: diagnostics go to stderr."""

import sys


def report(n):
    print(f"emitted {n} candidates", file=sys.stderr)

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL005 must pass: static bounds — literal ranges (the kernels' round
idiom) and shape-derived ranges (static at trace time)."""

import jax


@jax.jit
def fold(words):
    """uint32 [N, 16] -> uint32 [N]."""
    acc = words[:, 0]
    for i in range(1, 16):
        acc = acc ^ words[:, i]
    for j in range(words.shape[1]):
        acc = acc + j
    return acc

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL012 must flag: direct reads of A5GEN_* knobs outside runtime/env.py.

Every read form counts — ``os.environ.get``, ``os.getenv``, and a
``Load``-context subscript; sprawled reads fragment the knob surface
and let off-spelling vocabularies drift between subsystems.
"""

import os
from os import environ


def kernel_enabled() -> bool:
    return os.environ.get("A5GEN_PALLAS", "") != "off"  # direct read


def superstep_steps() -> str:
    return os.getenv("A5GEN_SUPERSTEP", "auto")  # direct read


def dcn_timeout() -> str:
    return environ["A5GEN_DCN_TIMEOUT"]  # direct subscript read

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL008 must pass: the package's shape/dtype docstring convention."""


def expand(tokens, lengths):
    """Expand candidates: ``uint8 [B, L], int32 [B] -> uint8 [N, W]``."""
    return tokens


def pack(rows):
    """Pack rows into launch order (shape-preserving, uint32)."""
    return rows


def _internal(buf):
    return buf

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL010 must pass: None sentinels, construction inside the body."""


def collect(hit, acc=None):
    if acc is None:
        acc = []
    acc.append(hit)
    return acc


def configure(overrides=None, *, tags=()):
    return dict(overrides or {}), tuple(tags)

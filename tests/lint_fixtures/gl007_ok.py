# graftlint-virtual-path: hashcat_a5_table_generator_tpu/tables/_fixture.py
"""GL007 must pass: canonical (sorted) orders, no entropy, no clocks."""


def canonical_keys(keys):
    return sorted(keys)


def stable_hash(data):
    acc = 0
    for b in data:
        acc = (acc * 31 + b) & 0xFFFFFFFF
    return acc

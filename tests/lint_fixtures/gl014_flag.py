# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL014 must flag: hardcoded geometry literals in ``runtime/``.

Every binding form counts — a bare assignment (shift spelling
included), a geometry keyword in a call, and a function default —
because each one pins a geometry the autotune profile can never
override and the ``geometry_source`` stamp never reports (PERF.md
§29)."""


def build(make_config):
    lanes = 1 << 20  # assignment: GL014
    stride = 128  # assignment: GL014
    cfg = make_config(num_blocks=1024)  # call keyword: GL014
    return lanes, stride, cfg


def drive(step, superstep=8):  # function default: GL014
    return step(superstep)

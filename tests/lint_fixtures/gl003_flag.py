# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL003 must flag: concretizing a tracer inside a jitted body."""

import jax


@jax.jit
def count_hits(hits):
    """bool [N] -> int scalar."""
    total = hits.sum().item()
    return total + int(hits)

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL012 stays quiet on the idiom: reads through the runtime/env.py
accessor (read_env/env_str/env_is), env WRITES (probe scripts pinning a
configuration), and reads of non-A5GEN variables (not this rule's
surface)."""

import os

from ..runtime.env import env_is, env_str, read_env


def kernel_enabled() -> bool:
    return env_str("A5GEN_PALLAS").lower() != "off"


def superstep_steps() -> str:
    return read_env("A5GEN_SUPERSTEP") or "auto"


def interpret_forced() -> bool:
    return env_is("A5GEN_PALLAS_INTERPRET", "1")


def pin_for_probe() -> None:
    os.environ["A5GEN_PALLAS"] = "expand"  # a WRITE: probe plumbing


def unrelated() -> str:
    return os.environ.get("XLA_FLAGS", "")  # not an A5GEN_ knob

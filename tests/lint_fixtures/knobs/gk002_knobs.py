"""Miniature registry for the GK002 fixture pair: one trace-role knob
whose token must appear in the step-cache key."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "stride": {
        "layers": {"config": {"surface": "stride", "default": 128}},
        "roles": ["trace"],
        "keys": {"trace": "stride"},
    },
}

"""GK004 clean twin: the affinity token routes 'devices' and the
fingerprint takes 'mode'."""


def static_affinity_token(**fields):
    return "|".join(f"{k}={v}" for k, v in sorted(fields.items()))


def affinity_token(spec, cfg):
    return static_affinity_token(
        lanes=cfg.lanes, blocks=cfg.num_blocks, devices=cfg.devices
    )


def sweep_fingerprint(mode, algo, words, sub_map):
    return hash((mode, algo, tuple(words), sub_map))

"""Miniature registry for the GK001 fixture pair: two declared env
knobs — the fixtures differ in which of them the surface file reads."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "alpha": {
        "layers": {"env": {"surface": "A5GEN_ALPHA", "default": None}},
        "roles": ["host-only"],
    },
    "beta": {
        "layers": {"env": {"surface": "A5GEN_BETA", "default": None}},
        "roles": ["host-only"],
    },
}

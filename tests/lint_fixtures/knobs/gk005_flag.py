"""GK005 broken fixture: the dataclass default AND the argparse
default drifted from the declared 131072."""


class SweepConfig:
    lanes: int = 65536


def build_parser(parser):
    parser.add_argument("--lanes", type=int, default=256)
    return parser

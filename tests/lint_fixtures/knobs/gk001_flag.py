"""GK001 broken fixture: an undeclared env read (A5GEN_GAMMA) and a
dead declaration (nothing here spells A5GEN_BETA)."""


def alpha_enabled(read_env):
    return read_env("A5GEN_ALPHA") == "1"


def gamma_enabled(read_env):
    return read_env("A5GEN_GAMMA") == "1"

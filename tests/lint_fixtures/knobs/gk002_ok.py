"""GK002 clean twin: the trace-role token rides the skey tuple."""


class Sweep:
    def _make_launch(self, plan):
        skey = (self.lanes, self.num_blocks, self.stride, plan.kind)
        return skey

"""GK002 broken fixture: the skey tuple never spells 'stride' — two
jobs differing only on stride would share one compiled program."""


class Sweep:
    def _make_launch(self, plan):
        skey = (self.lanes, self.num_blocks, plan.kind)
        return skey

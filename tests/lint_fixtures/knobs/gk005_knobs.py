"""Miniature registry for the GK005 fixture pair: one knob with a
declared default at both the config and the cli layer."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "lanes": {
        "layers": {
            "config": {"surface": "lanes", "default": 131072},
            "cli": {"surface": "--lanes", "default": 131072},
        },
        "roles": ["host-only"],
    },
}

"""Miniature registry for the GK004 fixture pair: one affinity-role
knob and one fingerprint-role knob."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "devices": {
        "layers": {"config": {"surface": "devices", "default": 1}},
        "roles": ["affinity"],
        "keys": {"affinity": "devices"},
    },
    "mode": {
        "layers": {"config": {"surface": "mode", "default": "default"}},
        "roles": ["fingerprint"],
        "keys": {"fingerprint": "mode"},
    },
}

"""GK001 clean twin: every read is declared, every declaration read."""


def alpha_enabled(read_env):
    return read_env("A5GEN_ALPHA") == "1"


def beta_enabled(read_env):
    return read_env("A5GEN_BETA") == "1"

"""GK003 clean twin: the knob gates eligibility (a return-None guard
counts exactly like key membership)."""


def pack_candidate(sweep, resume_state=None):
    cfg = sweep.config
    if cfg.stream_chunk_words is not None:
        return None
    if cfg.pod is not None:
        return None
    key = (cfg.lanes, cfg.num_blocks)
    return {"key": key}

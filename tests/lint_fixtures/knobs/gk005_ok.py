"""GK005 clean twin: both code defaults fold to the declared value
(1 << 17 folds to 131072)."""


class SweepConfig:
    lanes: int = 1 << 17


def build_parser(parser):
    parser.add_argument("--lanes", type=int, default=131072)
    return parser

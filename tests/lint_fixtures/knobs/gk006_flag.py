"""GK006 broken fixture: a knob was added since gk006_pin.json was
written (any drift flags; --update-knobs is the re-pin door)."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "alpha": {
        "layers": {"env": {"surface": "A5GEN_ALPHA", "default": None}},
        "roles": ["host-only"],
    },
    "beta": {
        "layers": {"env": {"surface": "A5GEN_BETA", "default": None}},
        "roles": ["host-only"],
    },
}

"""GK004 broken fixture: 'devices' never reaches the
static_affinity_token call, and 'mode' is not a sweep_fingerprint
parameter."""


def static_affinity_token(**fields):
    return "|".join(f"{k}={v}" for k, v in sorted(fields.items()))


def affinity_token(spec, cfg):
    return static_affinity_token(lanes=cfg.lanes, blocks=cfg.num_blocks)


def sweep_fingerprint(algo, words, sub_map):
    return hash((algo, tuple(words), sub_map))

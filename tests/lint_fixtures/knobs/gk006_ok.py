"""GK006 clean twin: the registry matches gk006_pin.json exactly."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "alpha": {
        "layers": {"env": {"surface": "A5GEN_ALPHA", "default": None}},
        "roles": ["host-only"],
    },
}

"""Miniature registry for the GK003 fixture pair: one fuse-compat-role
knob that must reach pack_candidate's key tuple or its guards."""

KNOBS_VERSION = "1.0"

KNOBS = {
    "pod": {
        "layers": {"config": {"surface": "pod", "default": None}},
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "pod"},
    },
}

"""GK003 broken fixture: 'pod' is in neither the compatibility key nor
any return-None guard — pod-striped jobs could fuse with solo ones
(the PR 12 bug class)."""


def pack_candidate(sweep, resume_state=None):
    cfg = sweep.config
    if cfg.stream_chunk_words is not None:
        return None
    key = (cfg.lanes, cfg.num_blocks)
    return {"key": key}

# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL011 must pass: pure lax loop bodies, host fetch AFTER the loop.

The superstep idiom: the scan carries device values only; the single
fetch after the loop is the completion barrier for the whole chain.
"""

import jax.numpy as jnp
import numpy as np
from jax import lax


def sweep_scan(plan, b0, steps):
    def step(carry, _):
        cursor, total = carry
        count = jnp.minimum(cursor, 128)
        return (cursor + 1, total + count), None

    carry, _ = lax.scan(step, (b0, jnp.zeros((), jnp.int32)), None,
                        length=steps)
    # Host sync OUTSIDE the loop: one fetch per superstep.
    return int(carry[1])


def summarize(batch):
    # np on plain host data outside any loop body is fine.
    counts = np.asarray(batch)
    return counts.sum()


def unrelated_helper(rows):
    # Shares a loop body's NAME but lives in a different scope: host
    # syncs here are ordinary host code, not per-iteration device work.
    def step(row, total):
        return total + int(row)

    acc = 0
    for r in rows:
        acc = step(r, acc)
    return acc

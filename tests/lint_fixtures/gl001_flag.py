# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL001 must flag: int literal wider than uint32 in ops/ arithmetic."""


def mix(x):
    """uint32 [N] lane mix."""
    return (x * 0x100000001) + 0x123456789AB

"""GW005 fixture: raw envelope-key literals outside the registry.

No miniature registry here — this file is NOT a registry source, so
every raw ``"op"``/``"event"`` KEY use below is sprawl: a dict key, a
``.get`` read, a subscript write, and a containment test.  Op/event
VALUE strings (``op == "submit"``) stay legal — graftrace GT004
extracts exactly those.
"""


def submit(sdoc, send):
    sdoc["op"] = "submit"            # GW005: subscript key
    send(sdoc)


def dispatch(doc):
    op = doc.get("op", "submit")     # GW005: .get read
    if "event" in doc:               # GW005: containment test
        return None
    return op


def ack(jid):
    return {"id": jid, "event": "accepted"}  # GW005: dict key

"""GW003 fixture: inline wire doc missing a declared-required field.

A ``failed`` without ``error`` and a ``hit`` without ``id`` — the two
shapes the check exists to catch before a client hangs on them.
"""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "failed": {"required": ["id", "error"], "optional": ["reason"],
               "emitters": ["engine"], "route": "dispatch"},
    "hit": {"required": ["id", "digest"], "optional": [],
            "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def fail(jid):
    return {"id": jid, "event": "failed"}  # GW003: no "error"


def hit(digest):
    return {"event": "hit", "digest": digest}  # GW003: no "id"

"""GW001 clean twin: every emitted/dispatched name is declared."""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
    "frobnicate": {"required": ["id"], "optional": [],
                   "handlers": ["engine"]},
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
    "vanished": {"required": ["id"], "optional": [],
                 "emitters": ["engine"], "route": "passthrough"},
    "acked": {"required": ["id"], "optional": [],
              "emitters": ["engine"], "route": "passthrough"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def ev_vanished(jid):
    return {"id": jid, "event": "vanished"}


class _Session:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "frobnicate":
            return None
        return None

    def emit_ack(self, jid):
        self._send({"id": jid, "event": "acked"})

    def _send(self, ev):
        raise NotImplementedError

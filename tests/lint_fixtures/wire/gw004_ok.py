"""GW004 clean twin: every handler read is a declared field."""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id", "payload"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def doc_op(doc):
    return doc.get("op", "submit")


class _Session:
    def _handle(self, doc):
        op = doc_op(doc)
        if op == "submit":
            return doc.get("payload")
        return None

"""GW006 fixture: live registry drifted from the committed pin.

Paired with ``gw006_pin.json``, which pins neither the ``probe`` op
this registry adds nor the ``retry_after_s`` field on ``failed`` —
drift in the addition direction.  (``gw006_ok.py`` matches the pin
exactly.)  Driven with ``--protocol-json`` / ``pin_path`` so the
repo's real PROTOCOL.json never leaks into the fixture.
"""

PROTOCOL_VERSION = "1.1"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
    "probe": {"required": ["id"], "optional": [],
              "handlers": ["engine"]},  # GW006: not in the pin
}

WIRE_EVENTS = {
    "failed": {"required": ["id", "error"],
               "optional": ["retry_after_s"],  # GW006: not pinned
               "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}

"""GW004 fixture: handler reads a field no declared sender can set.

``_handle`` dispatches only ``submit`` yet reads ``ghost`` — a field
no declared op carries, so the read sees its default forever.
"""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id", "payload"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def doc_op(doc):
    return doc.get("op", "submit")


class _Session:
    def _handle(self, doc):
        op = doc_op(doc)
        if op == "submit":
            return doc.get("ghost")  # GW004: nobody sets "ghost"
        return None

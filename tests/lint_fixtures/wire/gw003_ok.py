"""GW003 clean twin: every required field present (or the doc is
``**``-spread open, which the AST cannot enumerate and must skip)."""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "failed": {"required": ["id", "error"], "optional": ["reason"],
               "emitters": ["engine"], "route": "dispatch"},
    "hit": {"required": ["id", "digest"], "optional": [],
            "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def fail(jid, exc):
    return {"id": jid, "event": "failed", "error": str(exc)}


def hit(jid, digest):
    return {"id": jid, "event": "hit", "digest": digest}


def forwarded(base):
    return {"event": "failed", **base}  # open doc: fields unknowable

"""GW002 fixture: declared op/event with no handler at its role.

The registry declares op ``frob`` with the engine role as a handler,
but ``_JsonlSession._handle`` never decides it; event ``pulse`` routes
as ``dispatch``, but ``_on_job_event`` never decides it either.
"""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
    "frob": {"required": ["id"], "optional": [],
             "handlers": ["engine"]},  # GW002: engine never handles it
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
    "pulse": {"required": ["id"], "optional": [],
              "emitters": ["engine"],
              "route": "dispatch"},  # GW002: chain never decides it
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


class _JsonlSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "submit":
            return True
        return True


class _Router:
    def _on_job_event(self, link, ev):
        event = ev.get("event")
        if event == "done":
            return None
        return None

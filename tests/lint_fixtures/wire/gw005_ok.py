"""GW005 clean twin: emissions via constructors, reads via helpers.

The value strings in the dispatch chain are legal — GW005 bans only
the envelope KEY literals.
"""

import json


def submit(sdoc, send, protocol):
    send(protocol.op_submit(sdoc))


def dispatch(doc, protocol):
    op = protocol.doc_op(doc)
    if op == "submit":
        return "submitting"
    return json.dumps({"id": doc["id"]})


def ack(jid, protocol):
    return protocol.ev_accepted(jid, "crack")

"""GW001 fixture: emitted/dispatched op-event not in the registry.

Embeds a miniature registry (this file is its own registry source, the
fixture pattern graftwire's registry detection supports) and then
emits an event the registry never declared, dispatches an undeclared
op, and calls a constructor with no registry entry.
"""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


def ev_vanished(jid):
    return {"id": jid, "event": "vanished"}  # GW001: undeclared event


class _Session:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "frobnicate":  # GW001: undeclared op dispatched
            return None
        return None

    def emit_ack(self, jid):
        self._send({"id": jid, "event": "acked"})  # GW001

    def _send(self, ev):
        raise NotImplementedError

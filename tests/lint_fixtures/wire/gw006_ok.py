"""GW006 clean twin: the registry matches ``gw006_pin.json``."""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
}

WIRE_EVENTS = {
    "failed": {"required": ["id", "error"], "optional": [],
               "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}

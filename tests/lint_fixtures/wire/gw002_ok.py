"""GW002 clean twin: every declared handler obligation is met."""

PROTOCOL_VERSION = "1.0"

WIRE_OPS = {
    "submit": {"required": [], "optional": ["id"],
               "handlers": ["engine"], "default": True},
    "frob": {"required": ["id"], "optional": [],
             "handlers": ["engine"]},
}

WIRE_EVENTS = {
    "done": {"required": ["id"], "optional": [],
             "emitters": ["engine"], "route": "dispatch"},
    "pulse": {"required": ["id"], "optional": [],
              "emitters": ["engine"], "route": "dispatch"},
}

CHECKPOINT_WIRE = {"version": "1.0", "required": ["fingerprint"]}


class _JsonlSession:
    def _handle(self, doc):
        op = doc.get("op", "submit")
        if op == "submit":
            return True
        if op == "frob":
            return True
        return True


class _Router:
    def _on_job_event(self, link, ev):
        event = ev.get("event")
        if event in ("done", "pulse"):
            return None
        return None

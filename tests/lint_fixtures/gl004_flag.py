# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL004 must flag: host numpy applied to a traced argument."""

import jax
import numpy as np


@jax.jit
def checksum(words):
    """uint32 [N] -> uint32 scalar."""
    return np.bitwise_xor.reduce(np.asarray(words))

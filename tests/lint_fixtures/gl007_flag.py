# graftlint-virtual-path: hashcat_a5_table_generator_tpu/tables/_fixture.py
"""GL007 must flag: entropy and wall clock in a deterministic layer."""

import random
import time


def shuffle_keys(keys):
    random.shuffle(keys)
    return keys


def stamp():
    return time.time()

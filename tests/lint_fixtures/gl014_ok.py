# graftlint-virtual-path: hashcat_a5_table_generator_tpu/runtime/_fixture.py
"""GL014 stays quiet on the idiom: geometry flows through the
resolution seam — knobs left ``None`` for the Sweep to fill from the
device kind's profile, values read back off a config, and derived
geometry computed from those resolved values (PERF.md §29).  Non-
geometry integer literals are out of scope."""


def build(make_config, resolve_config, kind):
    cfg = make_config(lanes=None, num_blocks=None)  # resolve at launch
    resolved, source = resolve_config(cfg, kind)
    lanes = resolved.lanes  # read-back, not a literal
    stride = lanes // max(resolved.num_blocks, 1)  # derived
    fetch_chunk = 16  # not a geometry knob
    return resolved, source, stride, fetch_chunk


def drive(step, superstep=None):  # None default: resolved downstream
    return step(superstep)

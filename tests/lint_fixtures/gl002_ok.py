# graftlint-virtual-path: hashcat_a5_table_generator_tpu/ops/_fixture.py
"""GL002 must pass: integer-only kernel; host-side float stays host-side."""

import jax

#: Host-side tuning ratio (module scope, never traced).
HOST_RATIO = 1.5


def plan_budget(n):
    """Host helper: int scalar budget from a float ratio."""
    return int(n * HOST_RATIO)


@jax.jit
def scale(x):
    """uint32 [N] -> uint32 [N]."""
    return (x << 1) + 3

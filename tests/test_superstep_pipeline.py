"""Double-buffered superstep pipeline (PERF.md §18): the pipelined drive
must be STREAM-INVISIBLE next to the barriered drive and the per-launch
path — hits by full (word_index, rank, candidate) tuples, counts exact —
across match/suball (fallback interleave), windowed plans, 8-device
sharding, overflow replay, and mid-superstep resume including the
cross-path round trip (pipelined → per-launch → pipelined).  Plus the
``A5GEN_PIPELINE`` escape hatch and the ``--pipeline-ab`` bench record
shape (slow-marked: it compiles and times a subprocess bench).
"""

import hashlib
import json
import pathlib
import subprocess
import sys

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import (
    HitRecorder,
    Sweep,
    SweepConfig,
)
from tests.test_superstep import (
    LEET,
    WORDS,
    hit_tuples,
    oracle_lines,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_crack(spec, sub_map, words, digests, *, pipeline, superstep=None,
              devices=1, **cfg_kw):
    cfg = SweepConfig(lanes=64, num_blocks=16, superstep=superstep,
                      pipeline=pipeline, devices=devices, **cfg_kw)
    sweep = Sweep(spec, sub_map, words, digests, config=cfg)
    return sweep.run_crack()


class TestPipelineParity:
    """pipelined == barriered == per-launch, bit for bit."""

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_hits_and_counts_equal_across_drives(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(40)]

        piped = run_crack(spec, LEET, WORDS, digests, pipeline=True)
        barred = run_crack(spec, LEET, WORDS, digests, pipeline=False)
        launch = run_crack(spec, LEET, WORDS, digests, pipeline=None,
                           superstep=0)
        assert piped.n_emitted == barred.n_emitted == launch.n_emitted
        assert hit_tuples(piped) == hit_tuples(barred) == hit_tuples(launch)
        assert {h.candidate for h in piped.hits} == set(planted)
        assert piped.superstep["pipelined"] == 1
        assert barred.superstep["pipelined"] == 0
        assert launch.superstep == {}

    def test_deeper_pipeline_parity(self):
        # max_in_flight > 2 keeps the pre-§18 dispatch-ahead contract
        # (one buffer set per in-flight superstep, depth follows the
        # config) — a depth-3 drive must stay stream-identical to the
        # barriered one.
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[0]).digest(),
                   hashlib.md5(oracle[-1]).digest()]
        deep = run_crack(spec, LEET, WORDS, digests, pipeline=True,
                         max_in_flight=3)
        barred = run_crack(spec, LEET, WORDS, digests, pipeline=False)
        assert deep.superstep["pipelined"] == 1
        assert deep.n_emitted == barred.n_emitted
        assert hit_tuples(deep) == hit_tuples(barred)

    def test_suball_fallback_interleave(self):
        # Oracle-routed hazard words must interleave identically at the
        # pipeline's LAGGED superstep boundaries.
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
        words = [b"zz", b"acb", b"za", b"zacb", b"azz"]
        spec = AttackSpec(mode="suball", algo="md5")
        fb_cand = oracle_lines(spec, sub, [b"acb"])[-1]
        dev_cand = oracle_lines(spec, sub, [b"azz"])[-1]
        digests = [hashlib.md5(fb_cand).digest(),
                   hashlib.md5(dev_cand).digest()]

        cfg = SweepConfig(lanes=64, num_blocks=16, pipeline=True)
        sweep = Sweep(spec, sub, words, digests, config=cfg)
        assert sweep.fallback_rows, "fixture must exercise fallback"
        piped = sweep.run_crack()
        barred = run_crack(spec, sub, words, digests, pipeline=False)
        assert hit_tuples(piped) == hit_tuples(barred)
        assert {h.candidate for h in piped.hits} == {fb_cand, dev_cand}

    def test_windowed_plan_parity(self):
        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=1)
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[0]).digest(),
                   hashlib.md5(oracle[-1]).digest()]
        cfg = SweepConfig(lanes=64, num_blocks=16, pipeline=True)
        sweep = Sweep(spec, LEET, WORDS, digests, config=cfg)
        assert sweep.plan.windowed
        piped = sweep.run_crack()
        barred = run_crack(spec, LEET, WORDS, digests, pipeline=False)
        assert hit_tuples(piped) == hit_tuples(barred)
        assert piped.n_emitted == barred.n_emitted == len(oracle)

    def test_eight_device_sharded_parity(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[1], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]

        piped = run_crack(spec, LEET, WORDS, digests, pipeline=True,
                          devices=8)
        barred = run_crack(spec, LEET, WORDS, digests, pipeline=False,
                           devices=8)
        one = run_crack(spec, LEET, WORDS, digests, pipeline=True)
        assert hit_tuples(piped) == hit_tuples(barred) == hit_tuples(one)
        assert piped.n_emitted == barred.n_emitted == one.n_emitted
        assert piped.superstep["pipelined"] == 1

    def test_overflow_replay_under_pipeline(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, [b"password", b"sesame"])
        dense = [hashlib.md5(c).digest() for c in oracle[:40]]

        barred = run_crack(spec, LEET, WORDS, dense, pipeline=False,
                           superstep_hit_cap=8)
        piped = run_crack(spec, LEET, WORDS, dense, pipeline=True,
                          superstep_hit_cap=8)
        assert piped.superstep["replays"] >= 1
        assert hit_tuples(piped) == hit_tuples(barred)
        assert piped.n_hits == barred.n_hits == 40


class TestPipelineResume:
    def test_mid_sweep_resume_lands_at_lagged_boundary(self, tmp_path):
        """A crash with a superstep in flight leaves a checkpoint at the
        FETCHED (lagged) boundary; resume completes the identical
        stream — the in-flight superstep's work is simply redone."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[3], oracle[-2]})
        digests = [hashlib.md5(c).digest() for c in planted]
        want = run_crack(spec, LEET, WORDS, digests, pipeline=True)

        path = str(tmp_path / "pl.json")
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                          pipeline=True, checkpoint_path=path,
                          checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        from hashcat_a5_table_generator_tpu.runtime import load_checkpoint

        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None
        assert partial.cursor.word < len(WORDS)

        second = Sweep(spec, LEET, WORDS, digests, config=cfg)
        got = second.run_crack()
        assert got.resumed
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )

    def test_cross_path_resume_round_trip(self, tmp_path):
        """pipelined → per-launch → pipelined: a pipelined checkpoint is
        a plain (word, rank) cursor, resumable by the per-launch path,
        whose own checkpoint the pipeline can pick back up (the resume
        round-trip assert in _make_superstep guards the decode)."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[1], oracle[len(oracle) // 2], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        path = str(tmp_path / "cross.json")

        class Boom(Exception):
            pass

        def exploding(after):
            class R(HitRecorder):
                def emit(self, record):
                    super().emit(record)
                    if len(self.hits) >= after:
                        raise Boom()
            return R()

        cfg_piped = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                                pipeline=True, checkpoint_path=path,
                                checkpoint_every_s=0.0)
        with pytest.raises(Boom):
            Sweep(spec, LEET, WORDS, digests,
                  config=cfg_piped).run_crack(exploding(1))

        cfg_launch = SweepConfig(lanes=64, num_blocks=16, superstep=0,
                                 checkpoint_path=path,
                                 checkpoint_every_s=0.0)
        with pytest.raises(Boom):
            Sweep(spec, LEET, WORDS, digests,
                  config=cfg_launch).run_crack(exploding(2))

        got = Sweep(spec, LEET, WORDS, digests,
                    config=cfg_piped).run_crack()
        assert got.resumed
        want = run_crack(spec, LEET, WORDS, digests, pipeline=True)
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )
        assert {h.candidate for h in got.hits} == set(planted)


class TestEscapeHatches:
    def test_env_off_pins_barriered_drive(self, monkeypatch):
        monkeypatch.setenv("A5GEN_PIPELINE", "off")
        spec = AttackSpec(mode="default", algo="md5")
        digests = [hashlib.md5(b"nope").digest()]
        res = run_crack(spec, LEET, WORDS, digests, pipeline=None)
        assert res.superstep["supersteps"] >= 1
        assert res.superstep["pipelined"] == 0

    def test_env_typo_warns_and_keeps_default(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            pipeline_enabled,
        )

        monkeypatch.setenv("A5GEN_PIPELINE", "offf")
        assert pipeline_enabled()
        assert "A5GEN_PIPELINE" in capsys.readouterr().err

    def test_config_false_pins_barriered_drive(self):
        spec = AttackSpec(mode="default", algo="md5")
        digests = [hashlib.md5(b"nope").digest()]
        res = run_crack(spec, LEET, WORDS, digests, pipeline=False)
        assert res.superstep["pipelined"] == 0

    def test_single_in_flight_budget_disables_pipeline(self):
        # max_in_flight=1 forbids dispatch-ahead; auto must degrade to
        # the barriered drive, stream unchanged.
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        digests = [hashlib.md5(oracle[-1]).digest()]
        res = run_crack(spec, LEET, WORDS, digests, pipeline=None,
                        max_in_flight=1)
        assert res.superstep["pipelined"] == 0
        assert {h.candidate for h in res.hits} == {oracle[-1]}


@pytest.mark.slow
def test_bench_pipeline_ab_record_shape():
    """The §18 measurement instrument: one JSON line, both arms, the
    dead-time ratio the acceptance criterion reads.  Slow-marked: it
    compiles and times a subprocess bench (~1 min on the tier-1 host)."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pipeline-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "400", "--seconds", "2"],
        capture_output=True, timeout=240, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "pipeline_host_overhead_ab"
    for arm in ("barriered", "pipelined"):
        assert rec[arm]["hashes_per_sec"] > 0
        assert rec[arm]["launches"] >= 16
        assert rec[arm]["host_s_per_step"] >= 0
        assert 0.0 <= rec[arm]["overlap_ratio"] <= 1.0
    # The barriered arm never overlaps by construction.  The pipelined
    # arm's dead share undercutting it by the ≤0.5x acceptance bar is a
    # MEASUREMENT (PERF.md §18b), not a shape invariant — a preempted
    # host thread can open an un-overlapped gap in a 2 s window, so the
    # record-shape test only pins that SOME overlap happened.
    assert rec["barriered"]["overlap_ratio"] == 0.0
    assert rec["pipelined"]["overlap_ratio"] > 0.0
    assert rec["host_overhead_ratio"] > 1.0

"""Multi-process oracle (--threads N): the merged stream must be
byte-identical to the sequential (--threads 1, reference-order) path for
every mode — parallelism must be unobservable in the output."""

import hashlib
import io

import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.oracle.parallel import (
    OracleWorkerError,
    run_candidates_parallel,
    run_crack_parallel,
)
from hashcat_a5_table_generator_tpu.runtime.sinks import CandidateWriter

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"xyzzy", b"sass", b"apollo", b"essence"]


def _sequential_blob(words, sub, hex_unsafe=False, **kw) -> bytes:
    buf = io.BytesIO()
    w = CandidateWriter(buf, hex_unsafe=hex_unsafe)
    for word in words:
        for cand in iter_candidates(word, sub, **kw):
            w.emit(cand)
    w.flush()
    return buf.getvalue()


@pytest.mark.parametrize("mode_kw", [
    dict(),
    dict(reverse=True),
    dict(substitute_all=True),
    dict(substitute_all=True, reverse=True),
    dict(min_substitute=1, max_substitute=2),
])
@pytest.mark.parametrize("n_workers", [2, 3])
def test_candidates_byte_identical(mode_kw, n_workers):
    want = _sequential_blob(WORDS, LEET, **mode_kw)
    buf = io.BytesIO()
    writer = CandidateWriter(buf)
    n = run_candidates_parallel(
        WORDS, LEET, writer, n_workers=n_workers, **mode_kw
    )
    writer.flush()
    assert buf.getvalue() == want
    assert n == want.count(b"\n")


def test_hex_unsafe_wrapping_matches():
    sub = {b"a": [b"\x0a"], b"b": [b"\r"]}  # values that corrupt lines
    words = [b"abba", b"baab", b"cab"]
    want = _sequential_blob(words, sub, hex_unsafe=True)
    assert b"$HEX[" in want  # the wrapping actually engages
    buf = io.BytesIO()
    writer = CandidateWriter(buf, hex_unsafe=True)
    run_candidates_parallel(words, sub, writer, n_workers=2,
                            hex_unsafe=True)
    writer.flush()
    assert buf.getvalue() == want


def test_more_workers_than_words():
    words = [b"sos", b"as"]
    want = _sequential_blob(words, LEET)
    buf = io.BytesIO()
    writer = CandidateWriter(buf)
    run_candidates_parallel(words, LEET, writer, n_workers=8)
    writer.flush()
    assert buf.getvalue() == want


def test_crack_hits_in_word_order():
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, LEET))
    planted = [oracle[3], oracle[len(oracle) // 2], oracle[-2]]
    digs = [hashlib.md5(c).digest() for c in planted]
    digs += [hashlib.md5(b"decoy%d" % i).digest() for i in range(30)]

    # Sequential expectation: (digest, cand) in stream order.
    want = []
    lookup = set(digs)
    for w in WORDS:
        for cand in iter_candidates(w, LEET):
            d = hashlib.md5(cand).digest()
            if d in lookup:
                want.append((d.hex(), cand))

    got = []
    n = run_crack_parallel(
        WORDS, LEET, digs, "md5",
        lambda dh, c: got.append((dh, c)), n_workers=3,
    )
    assert got == want
    assert n == len(want) >= 3


def test_worker_error_propagates():
    bad = {b"a": [b"4"]}

    class Boom(bytes):
        pass

    # A word that makes the engine raise inside the worker: oversized
    # bytes are fine, so inject failure via a non-bytes word.
    with pytest.raises((OracleWorkerError, TypeError, AttributeError)):
        run_candidates_parallel(
            [b"ok", 12345, b"ok2"], bad,
            CandidateWriter(io.BytesIO()), n_workers=2,
        )

"""graftaudit corpus: every semantic check must FIRE on its
deliberately-broken fixture and stay quiet on the clean twin.

The fixtures (tests/lint_fixtures/audit/) are real traceable jax
programs — the audit operates on jaxprs and optimized HLO, not source —
kept tiny so the whole suite traces/compiles in seconds on the CPU
backend.  The full-repo audit itself (every registered entry, the
KERNEL_BUDGETS.json gate) runs as a blocking CI step; here we pin the
check MACHINERY plus the cheap repo-level contracts (registry/harness
coverage, one budget tier against the committed pin).
"""

import importlib.util
import pathlib
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftaudit import (  # noqa: E402
    AuditFinding,
    audit_float_purity,
    audit_host_transfers,
    audit_pallas,
    audit_stages,
    compare_budgets,
    count_traced_kernel,
    load_budgets,
)

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "lint_fixtures" / "audit"


def _fixture(name):
    spec = importlib.util.spec_from_file_location(
        f"audit_fixture_{name}", FIXTURE_DIR / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Dead-stage detection (the PERF.md §15 membership-DCE reproduction)
# ---------------------------------------------------------------------------


class TestDeadStage:
    def test_broken_body_loses_membership(self):
        mod = _fixture("dce_membership")
        findings = audit_stages(
            mod.broken_body, mod.example_args(), "fixture.dce", mod.STAGES
        )
        assert findings, "membership DCE not detected"
        assert all(f.check == "dead-stage" for f in findings)
        dead = {f.message.split(" ")[1] for f in findings}
        assert "membership" in dead  # the §15 trap itself

    def test_clean_body_keeps_all_stages(self):
        mod = _fixture("dce_membership")
        findings = audit_stages(
            mod.clean_body, mod.example_args(), "fixture.dce", mod.STAGES
        )
        assert findings == []

    def test_perslot_broken_body_loses_membership(self):
        # §15 trap regression against the PER-SLOT emission body
        # (PERF.md §17): the rewritten expand stage must not hide the
        # membership DCE from the stage markers.
        mod = _fixture("dce_perslot")
        findings = audit_stages(
            mod.broken_body, mod.example_args(), "fixture.dce_perslot",
            mod.STAGES,
        )
        assert findings, "membership DCE not detected on the piece body"
        dead = {f.message.split(" ")[1] for f in findings}
        assert "membership" in dead

    def test_perslot_clean_body_keeps_all_stages(self):
        mod = _fixture("dce_perslot")
        findings = audit_stages(
            mod.clean_body, mod.example_args(), "fixture.dce_perslot",
            mod.STAGES,
        )
        assert findings == []


# ---------------------------------------------------------------------------
# Float purity
# ---------------------------------------------------------------------------


class TestFloatLeak:
    def test_broken_stage_flagged(self):
        mod = _fixture("float_leak")
        findings = audit_float_purity(
            mod.broken_stage, mod.example_args(), "fixture.float"
        )
        assert len(findings) == 1
        assert findings[0].check == "float-leak"
        assert "float" in findings[0].message

    def test_clean_stage_passes(self):
        mod = _fixture("float_leak")
        assert audit_float_purity(
            mod.clean_stage, mod.example_args(), "fixture.float"
        ) == []


# ---------------------------------------------------------------------------
# Host transfers in loop bodies
# ---------------------------------------------------------------------------


class TestHostTransfer:
    def test_callback_in_scan_flagged_as_per_step(self):
        mod = _fixture("host_transfer")
        findings = audit_host_transfers(
            mod.broken_sweep, mod.example_args(), "fixture.transfer"
        )
        assert findings, "debug.print in scan body not detected"
        assert all(f.check == "host-transfer" for f in findings)
        assert any("per step" in f.message for f in findings)

    def test_clean_scan_passes(self):
        mod = _fixture("host_transfer")
        assert audit_host_transfers(
            mod.clean_sweep, mod.example_args(), "fixture.transfer"
        ) == []


# ---------------------------------------------------------------------------
# Pipelined drive-loop fetch discipline (PERF.md §18)
# ---------------------------------------------------------------------------


class TestDriveFetch:
    def test_double_fetch_and_inflight_fetch_flagged(self):
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        findings = audit_drive_loop(mod.broken_drive, "fixture.drive")
        assert all(f.check == "drive-fetch" for f in findings)
        # Both regressions: barriering the in-flight superstep and the
        # second unconditional fetch of the popped one.
        assert any("in-flight" in f.message for f in findings)
        assert any("unconditional" in f.message for f in findings)

    def test_unbound_dispatch_fetch_flagged(self):
        # The production dispatch shape binds nothing (the call result
        # goes straight into the deque) — fetching the in-flight
        # superstep THROUGH the container must still be a finding.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        findings = audit_drive_loop(
            mod.broken_drive_unbound, "fixture.drive"
        )
        assert any("in-flight" in f.message for f in findings)

    def test_guard_fetch_flagged(self):
        # A fetch written as the hit guard's TEST runs every superstep
        # — it must count as the second unconditional fetch.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        findings = audit_drive_loop(
            mod.broken_drive_guard_fetch, "fixture.drive"
        )
        assert any("unconditional" in f.message for f in findings)

    def test_clean_drive_passes(self):
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        assert audit_drive_loop(mod.clean_drive, "fixture.drive") == []

    def test_clean_drive_inline_coercion_passes(self):
        # ``int(np.asarray(out[...])[0])`` is ONE round trip (the inner
        # asarray); the outer coercion must not be double-counted.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        assert audit_drive_loop(
            mod.clean_drive_inline_coercion, "fixture.drive"
        ) == []

    def test_loop_fetch_flagged(self):
        # A single fetch call NODE inside a nested loop is N round
        # trips per superstep — the double-fetch regression written as
        # a loop must still trip the exactly-one tally.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        findings = audit_drive_loop(
            mod.broken_drive_loop_fetch, "fixture.drive"
        )
        assert any("unconditional" in f.message for f in findings)

    def test_clean_drive_annotated_passes(self):
        # A `with` block (profiler annotation) does not gate its body:
        # the guarded hit fetch nested inside it must stay conditional
        # instead of being flat-walked into a second unconditional one.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        assert audit_drive_loop(
            mod.clean_drive_annotated, "fixture.drive"
        ) == []

    def test_clean_drive_bound_counters_passes(self):
        # Binding the fetched counters to a name and subscript-coercing
        # it (``counters = np.asarray(...); int(counters[0])``) is host
        # arithmetic after ONE round trip — a valid refactor of the
        # generator shape, not a double fetch.
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        assert audit_drive_loop(
            mod.clean_drive_bound_counters, "fixture.drive"
        ) == []

    def test_block_until_ready_flagged(self, tmp_path):
        import importlib.util
        import textwrap

        from tools.graftaudit.transfers import audit_drive_loop

        p = tmp_path / "sync_fx.py"
        p.write_text(textwrap.dedent(
            """
            def synced_drive(pending, call):
                while pending:
                    out = pending.popleft()
                    out['hit_word'].block_until_ready()
                    ne = int(out['counters'])
            """
        ))
        spec = importlib.util.spec_from_file_location("sync_fx", p)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        findings = audit_drive_loop(m.synced_drive, "fixture.sync")
        assert any("block_until_ready" in f.message for f in findings)

    def test_clean_recovering_drive_passes(self):
        """The fault-supervision try (PERF.md §23) wraps the fill loop
        and the one counters fetch; the audit must keep seeing exactly
        one unconditional fetch through it."""
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        assert audit_drive_loop(
            mod.clean_drive_recovering, "fixture.drive"
        ) == []

    def test_recovering_inflight_fetch_still_flagged(self):
        """The Try must not HIDE the fill loop from the in-flight
        tracking: a fetch through the deque inside the recovery try is
        the same pipeline barrier it always was."""
        from tools.graftaudit.transfers import audit_drive_loop

        mod = _fixture("double_fetch")
        findings = audit_drive_loop(
            mod.broken_drive_recovering_inflight_fetch, "fixture.drive"
        )
        assert any("in-flight" in f.message for f in findings)

    def test_production_drive_loop_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep
        from tools.graftaudit.transfers import audit_drive_loop

        assert audit_drive_loop(
            Sweep._drive_superstep, "runtime.Sweep._drive_superstep"
        ) == []


# ---------------------------------------------------------------------------
# Fault-injection hook shape (PERF.md §23)
# ---------------------------------------------------------------------------


class TestFaultHooks:
    def test_clean_guarded_hooks_pass(self):
        from tools.graftaudit.faults import audit_fault_hooks

        mod = _fixture("fault_hook")
        assert audit_fault_hooks(mod.clean_drive_hooked, "fixture.fh") == []
        assert audit_fault_hooks(
            mod.clean_drive_hooked_recovering, "fixture.fh"
        ) == []

    def test_bare_hook_flagged(self):
        from tools.graftaudit.faults import audit_fault_hooks

        mod = _fixture("fault_hook")
        findings = audit_fault_hooks(
            mod.broken_drive_bare_hook, "fixture.fh"
        )
        assert len(findings) == 1
        assert findings[0].check == "fault-hook"
        assert "ACTIVE-is-not-None" in findings[0].message

    def test_wrong_guard_flagged(self):
        from tools.graftaudit.faults import audit_fault_hooks

        mod = _fixture("fault_hook")
        findings = audit_fault_hooks(
            mod.broken_drive_wrong_guard, "fixture.fh"
        )
        assert [f.check for f in findings] == ["fault-hook"]

    def test_production_hook_sites_are_clean(self):
        from hashcat_a5_table_generator_tpu.ops.packing import (
            ChunkCompiler,
        )
        from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
            save_checkpoint,
        )
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from hashcat_a5_table_generator_tpu.runtime.fuse import FusedGroup
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep
        from tools.graftaudit.faults import audit_fault_hooks

        from hashcat_a5_table_generator_tpu.runtime.autoscale import (
            Autoscaler,
        )
        from hashcat_a5_table_generator_tpu.runtime.fleet import (
            EngineLink,
            FleetRouter,
        )

        for fn, name in (
            (Sweep._drive_superstep, "Sweep._drive_superstep"),
            (Sweep._dispatch_launch, "Sweep._dispatch_launch"),
            (Sweep._make_launch, "Sweep._make_launch"),
            (FusedGroup.pump, "FusedGroup.pump"),
            (Engine._build_slot, "Engine._build_slot"),
            (ChunkCompiler._timed, "ChunkCompiler._timed"),
            (save_checkpoint, "save_checkpoint"),
            (FleetRouter._dispatch, "FleetRouter._dispatch"),
            (EngineLink.send, "EngineLink.send"),
            (EngineLink.health_request, "EngineLink.health_request"),
            (Autoscaler._scale_up, "Autoscaler._scale_up"),
        ):
            assert audit_fault_hooks(fn, name) == [], name

    def test_router_shaped_fixture_variants(self):
        """The §27 fleet seams' shapes, as fixtures: a guarded hook at
        a dispatch entry (clean) and a bare hook inside a spawn try
        (broken) — the audit must distinguish them exactly as it does
        the drive-loop shapes."""
        from tools.graftaudit.faults import audit_fault_hooks

        mod = _fixture("fault_hook")
        assert audit_fault_hooks(
            mod.clean_router_dispatch_hooked, "fixture.fh"
        ) == []
        findings = audit_fault_hooks(
            mod.broken_spawn_bare_hook, "fixture.fh"
        )
        assert [f.check for f in findings] == ["fault-hook"]

    def test_production_pump_is_clean_for_pack_round(self):
        """The pump's fault-supervision restructure (PERF.md §23) must
        keep the packed-round discipline: one dispatch site, one
        unconditional fetch."""
        from hashcat_a5_table_generator_tpu.runtime.fuse import FusedGroup
        from tools.graftaudit.transfers import audit_pack_round

        assert audit_pack_round(
            FusedGroup.pump, "runtime.fuse.FusedGroup.pump"
        ) == []


# ---------------------------------------------------------------------------
# Streaming chunk-ring consume discipline (PERF.md §19)
# ---------------------------------------------------------------------------


class TestChunkRing:
    def test_clean_ring_passes(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        assert audit_chunk_ring(mod.clean_ring, "fixture.ring") == []

    def test_transfer_in_loop_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(
            mod.broken_ring_transfer, "fixture.ring"
        )
        assert any("asarray" in f.message for f in findings)
        assert all(f.check == "chunk-ring" for f in findings)

    def test_compile_in_loop_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(
            mod.broken_ring_compile, "fixture.ring"
        )
        assert any("build_plan" in f.message for f in findings)

    def test_materialized_ring_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(
            mod.broken_ring_materialized, "fixture.ring"
        )
        assert any("materializ" in f.message for f in findings)

    def test_conditional_release_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(
            mod.broken_ring_conditional_release, "fixture.ring"
        )
        assert any("release" in f.message for f in findings)

    def test_missing_release_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(
            mod.broken_ring_no_release, "fixture.ring"
        )
        assert any("release" in f.message for f in findings)

    def test_hoarded_chunk_flagged(self):
        from tools.graftaudit.transfers import audit_chunk_ring

        mod = _fixture("chunk_ring")
        findings = audit_chunk_ring(mod.broken_ring_hoard, "fixture.ring")
        assert any("container" in f.message for f in findings)

    def test_production_chunk_ring_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep
        from tools.graftaudit.transfers import audit_chunk_ring

        assert audit_chunk_ring(
            Sweep._sweep_chunks, "runtime.Sweep._sweep_chunks"
        ) == []


# ---------------------------------------------------------------------------
# Resident-engine serve-round discipline (PERF.md §20)
# ---------------------------------------------------------------------------


class TestServeLoop:
    def test_clean_round_passes(self):
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        assert audit_serve_loop(mod.clean_round, "fixture.serve") == []

    def test_drain_monopolization_flagged(self):
        # Draining one job to completion inside the round starves the
        # other tenants — the monopolization regression.
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_drain_round, "fixture.serve"
        )
        assert any("monopoliz" in f.message for f in findings)
        assert all(f.check == "serve-loop" for f in findings)

    def test_guarded_drain_monopolization_flagged(self):
        # The drain loop hidden under if/try still monopolizes — the
        # nesting flag must survive every statement shape.
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_guarded_drain_round, "fixture.serve"
        )
        assert any("monopoliz" in f.message for f in findings)

    def test_condition_drain_flagged(self):
        # The drain written as a while CONDITION still runs per
        # iteration — loop heads count as looped ticks.
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_condition_drain_round, "fixture.serve"
        )
        assert any("monopoliz" in f.message for f in findings)

    def test_double_tick_flagged(self):
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_double_tick_round, "fixture.serve"
        )
        assert any("2 machine tick" in f.message for f in findings)

    def test_fetch_in_round_flagged(self):
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_fetch_round, "fixture.serve"
        )
        assert any("fetch" in f.message for f in findings)

    def test_block_until_ready_flagged(self):
        from tools.graftaudit.transfers import audit_serve_loop

        mod = _fixture("serve_loop")
        findings = audit_serve_loop(
            mod.broken_sync_round, "fixture.serve"
        )
        assert any("block_until_ready" in f.message for f in findings)

    def test_production_serve_round_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from tools.graftaudit.transfers import audit_serve_loop

        assert audit_serve_loop(
            Engine._serve_round, "runtime.Engine._serve_round"
        ) == []


# ---------------------------------------------------------------------------
# Cross-job packed round discipline (PERF.md §22)
# ---------------------------------------------------------------------------


class TestPackRound:
    def test_clean_packed_round_passes(self):
        from tools.graftaudit.transfers import audit_pack_round

        mod = _fixture("serve_loop")
        assert audit_pack_round(
            mod.clean_packed_round, "fixture.pack"
        ) == []

    def test_perjob_dispatch_flagged(self):
        # The per-job-dispatch regression: a dispatch inside the member
        # loop degrades the packed round back to N round trips.
        from tools.graftaudit.transfers import audit_pack_round

        mod = _fixture("serve_loop")
        findings = audit_pack_round(
            mod.broken_packed_perjob_dispatch, "fixture.pack"
        )
        assert any("per-job-dispatch" in f.message for f in findings)
        assert all(f.check == "pack-round" for f in findings)

    def test_segment_bookkeeping_fetch_flagged(self):
        # A fetch hidden in the per-member segment bookkeeping barriers
        # the round once per member.
        from tools.graftaudit.transfers import audit_pack_round

        mod = _fixture("serve_loop")
        findings = audit_pack_round(
            mod.broken_packed_segment_fetch, "fixture.pack"
        )
        assert any(
            "fetch inside a for loop" in f.message for f in findings
        )

    def test_double_fetch_flagged(self):
        from tools.graftaudit.transfers import audit_pack_round

        mod = _fixture("serve_loop")
        findings = audit_pack_round(
            mod.broken_packed_double_fetch, "fixture.pack"
        )
        assert any("unconditional" in f.message for f in findings)

    def test_production_pack_round_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.fuse import FusedGroup
        from tools.graftaudit.transfers import audit_pack_round

        assert audit_pack_round(
            FusedGroup.pump, "runtime.fuse.FusedGroup.pump"
        ) == []


# ---------------------------------------------------------------------------
# Split-merge round discipline (PERF.md §31)
# ---------------------------------------------------------------------------


class TestMergeLoop:
    def test_clean_merge_passes(self):
        from tools.graftaudit.transfers import audit_merge_loop

        mod = _fixture("merge_loop")
        assert audit_merge_loop(mod.CleanMerge, "fixture.merge") == []

    def test_pershard_decode_flagged(self):
        # The per-shard-parse regression: the drain scan re-decodes the
        # wire event once per shard per hit.
        from tools.graftaudit.transfers import audit_merge_loop

        mod = _fixture("merge_loop")
        findings = audit_merge_loop(
            mod.BrokenPerShardDecode, "fixture.merge"
        )
        assert any(
            "decode inside a for loop" in f.message for f in findings
        )
        assert all(f.check == "merge-loop" for f in findings)

    def test_double_decode_flagged(self):
        from tools.graftaudit.transfers import audit_merge_loop

        mod = _fixture("merge_loop")
        findings = audit_merge_loop(
            mod.BrokenDoubleDecode, "fixture.merge"
        )
        assert any("unconditional" in f.message for f in findings)

    def test_unbounded_buffer_flagged(self):
        from tools.graftaudit.transfers import audit_merge_loop

        mod = _fixture("merge_loop")
        findings = audit_merge_loop(mod.BrokenHoard, "fixture.merge")
        assert any("_hoard" in f.message for f in findings)
        assert any("unbounded" in f.message for f in findings)

    def test_production_merge_round_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.fleet import (
            _SplitMerge,
        )
        from tools.graftaudit.transfers import audit_merge_loop

        assert audit_merge_loop(
            _SplitMerge, "runtime.fleet._SplitMerge._merge_round"
        ) == []


# ---------------------------------------------------------------------------
# Telemetry placement (PERF.md §21): off the hot path
# ---------------------------------------------------------------------------


class TestTelemetryAudit:
    def test_clean_drive_passes(self):
        from tools.graftaudit.telemetry import audit_telemetry

        mod = _fixture("telemetry_span")
        assert audit_telemetry(mod.clean_drive, "fixture.tl") == []

    def test_inflight_window_record_flagged(self):
        # A span record inside the dispatch fill loop — host work in
        # the in-flight window eats the pipeline overlap.
        from tools.graftaudit.telemetry import audit_telemetry

        mod = _fixture("telemetry_span")
        findings = audit_telemetry(
            mod.broken_drive_inflight, "fixture.tl"
        )
        assert any("in-flight window" in f.message for f in findings)
        assert all(f.check == "telemetry" for f in findings)

    def test_clean_scan_passes(self):
        from tools.graftaudit.telemetry import audit_telemetry

        mod = _fixture("telemetry_span")
        assert audit_telemetry(mod.clean_scan, "fixture.tl") == []

    def test_scan_body_record_flagged(self):
        # A registry call inside a scan body handed to jit: trace-time
        # lies at best, a smuggled per-step host round trip at worst.
        from tools.graftaudit.telemetry import audit_telemetry

        mod = _fixture("telemetry_span")
        findings = audit_telemetry(mod.broken_scan, "fixture.tl")
        assert any("traced body" in f.message for f in findings)

    def test_production_drive_loop_is_clean(self):
        from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep
        from tools.graftaudit.telemetry import audit_telemetry

        assert audit_telemetry(
            Sweep._drive_superstep, "runtime.Sweep._drive_superstep"
        ) == []
        assert audit_telemetry(
            Sweep._launches, "runtime.Sweep._launches"
        ) == []

    def test_production_step_builders_are_clean(self):
        import hashcat_a5_table_generator_tpu.models.attack as attack
        import hashcat_a5_table_generator_tpu.parallel.mesh as mesh
        from tools.graftaudit.telemetry import audit_telemetry_module

        assert audit_telemetry_module(attack) == []
        assert audit_telemetry_module(mesh) == []


# ---------------------------------------------------------------------------
# Pallas bounds + grid overlap
# ---------------------------------------------------------------------------


class TestPallasBounds:
    def test_oob_store_flagged(self):
        mod = _fixture("pallas_oob")
        findings = audit_pallas(
            mod.broken_launch, "fixture.oob", *mod.example_args()
        )
        assert findings, "static OOB store not detected"
        assert all(f.check == "pallas-bounds" for f in findings)
        assert any("index 4" in f.message for f in findings)

    def test_traced_constant_dslice_oob_flagged(self):
        """A Literal (0-d array) dslice start must still resolve
        statically — pallas itself cannot validate this form."""
        mod = _fixture("pallas_oob")
        findings = audit_pallas(
            mod.broken_launch_dslice, "fixture.oob", *mod.example_args()
        )
        assert findings, "traced-constant OOB dslice not detected"
        assert all(f.check == "pallas-bounds" for f in findings)

    def test_in_bounds_store_passes(self):
        mod = _fixture("pallas_oob")
        assert audit_pallas(
            mod.clean_launch, "fixture.oob", *mod.example_args()
        ) == []

    def test_overlapping_grid_writes_flagged(self):
        mod = _fixture("pallas_overlap")
        findings = audit_pallas(
            mod.broken_launch, "fixture.overlap", *mod.example_args()
        )
        assert findings, "overlapping grid writes not detected"
        assert all(f.check == "pallas-race" for f in findings)
        assert any("not injective" in f.message for f in findings)

    def test_injective_grid_passes(self):
        mod = _fixture("pallas_overlap")
        assert audit_pallas(
            mod.clean_launch, "fixture.overlap", *mod.example_args()
        ) == []


# ---------------------------------------------------------------------------
# Budget gate (pure comparison logic + one measured tier vs the pin)
# ---------------------------------------------------------------------------


class TestBudgets:
    BUDGETS = {
        "tolerance_pct": 2.0,
        "kernels": {
            "scalar": {"ops_per_candidate": 1000.0, "config": ""},
            "ghost": {"ops_per_candidate": 50.0, "config": ""},
        },
    }

    def test_drift_beyond_tolerance_fails_both_directions(self):
        for measured, sign in ((1025.0, "+"), (975.0, "-")):
            findings, rows = compare_budgets(
                {"scalar": measured, "ghost": 50.0}, self.BUDGETS
            )
            budget = [f for f in findings if f.check == "budget"]
            assert len(budget) == 1 and "scalar" == budget[0].entry
            assert sign in budget[0].message
            assert any(r[0] == "scalar" and r[4] == "DRIFT" for r in rows)

    def test_within_tolerance_passes(self):
        findings, rows = compare_budgets(
            {"scalar": 1015.0, "ghost": 50.0}, self.BUDGETS
        )
        assert [f for f in findings if f.check == "budget"] == []
        assert all(r[4] == "ok" for r in rows)

    def test_unpinned_and_unmeasured_are_config_findings(self):
        findings, _ = compare_budgets({"scalar": 1000.0}, self.BUDGETS)
        assert any(
            f.check == "config" and f.entry == "ghost" for f in findings
        )
        findings, _ = compare_budgets(
            {"scalar": 1000.0, "ghost": 50.0, "new": 10.0}, self.BUDGETS
        )
        assert any(
            f.check == "config" and f.entry == "new" for f in findings
        )

    def test_drifted_pin_fails_the_real_cli(self):
        """End-to-end budget-drift fixture: the CLI against a budgets
        file whose scalar pin is ~6% off must exit 1 with a named
        ``budget scalar`` finding (the other five tiers stay green, so
        the failure is attributable)."""
        proc = subprocess.run(
            [
                sys.executable, "-m", "tools.graftaudit",
                "--select", "budgets",
                "--budgets", str(FIXTURE_DIR / "budgets_drift.json"),
            ],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "budget scalar:" in proc.stdout
        assert "DRIFT" in proc.stderr  # the diff table names the tier

    def test_committed_pin_matches_live_scalar_count(self):
        """The cheap end-to-end anchor: the committed KERNEL_BUDGETS.json
        'scalar' tier must match a live trace+count (the CI graftaudit
        step checks every tier; this keeps the contract in tier-1)."""
        from tools.graftaudit import harness

        budgets = load_budgets()
        cfg = harness.budget_configs()["scalar"]
        fn, g, s = cfg.build()
        ops, _ = count_traced_kernel(fn, g, s)
        pinned = budgets["kernels"]["scalar"]["ops_per_candidate"]
        tol = budgets["tolerance_pct"] / 100.0
        assert abs(ops - pinned) <= pinned * tol


# ---------------------------------------------------------------------------
# Registry/harness coverage + CLI contract
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_every_registered_entry_has_a_config(self):
        from tools.graftaudit import harness

        findings = harness.coverage_findings()
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_registry_spans_all_three_layers(self):
        """The audit surface covers ops/, models/ AND parallel/ — losing
        a layer's registrations must fail loudly."""
        from tools.graftaudit import harness

        modules = {e.module for e in harness.registered_entries().values()}
        for layer in (".ops.", ".models.", ".parallel."):
            assert any(layer in m for m in modules), f"no entries in {layer}"

    def test_finding_render_contract(self):
        f = AuditFinding("budget", "scalar", "drifted")
        assert f.render() == "budget scalar: drifted"

    def test_reload_of_audited_module_is_idempotent(self):
        """importlib.reload re-executes @audited_entry decorations (a
        pattern tests/test_native.py already uses); same module+qualname
        must re-register, not raise."""
        import importlib

        from hashcat_a5_table_generator_tpu.ops import hashes

        importlib.reload(hashes)  # raises if registration isn't idempotent

    def test_conflicting_registration_still_raises(self):
        from hashcat_a5_table_generator_tpu.audit import audited_entry

        with pytest.raises(ValueError, match="registered twice"):
            @audited_entry("ops.hashes.md5", kind="integer_stage")
            def md5():  # a DIFFERENT callable claiming the name
                pass


@pytest.mark.slow
class TestFullAudit:
    def test_repo_audit_clean_and_under_budget(self):
        """`python -m tools.graftaudit` passes clean on the repo inside
        the 120 s acceptance budget (CI runs this as a blocking step;
        slow-marked here to keep tier-1 wall down)."""
        import time

        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftaudit"],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=600,
        )
        elapsed = time.monotonic() - t0
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert elapsed < 120, f"audit took {elapsed:.0f}s (budget 120s)"

"""CPU<->TPU parity for default- and reverse-mode expansion.

Per-word candidate multisets from the device kernel must equal the oracle's
(``process_word`` / ``process_word_reverse(bug_compat=False)``) for every
word — these modes have no fallback path (SURVEY.md Q1/Q2/Q5/Q6/Q7 vectors
are all exercised below)."""

from collections import Counter

import jax.numpy as jnp
import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import (
    iter_candidates,
    process_word,
    process_word_reverse,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.expand_matches import (
    build_match_plan,
    expand_matches,
    find_matches,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import BUILTIN_LAYOUTS


def run_device(sub_map, words, min_sub, max_sub, *, reverse=False, lanes=4096):
    """Enumerate the full variant space on the device path; returns
    {word_index: Counter(candidates)}."""
    ct = compile_table(sub_map)
    packed = pack_words(words)
    plan = build_match_plan(ct, packed, first_option_only=reverse)
    eff_min = min_sub if reverse else max(1, min_sub)
    results = {i: Counter() for i in range(len(words))}
    w, rank = 0, 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=lanes
        )
        if batch.total == 0:
            break
        cand, cand_len, word_row, emit = expand_matches(
            jnp.asarray(plan.tokens),
            jnp.asarray(plan.lengths),
            jnp.asarray(plan.match_pos),
            jnp.asarray(plan.match_len),
            jnp.asarray(plan.match_radix),
            jnp.asarray(plan.match_val_start),
            jnp.asarray(ct.val_bytes),
            jnp.asarray(ct.val_len),
            jnp.asarray(batch.word),
            jnp.asarray(batch.base_digits),
            jnp.asarray(batch.count),
            jnp.asarray(batch.offset),
            num_lanes=lanes,
            out_width=plan.out_width,
            min_substitute=eff_min,
            max_substitute=max_sub,
        )
        cand = np.asarray(cand)
        cand_len = np.asarray(cand_len)
        word_row = np.asarray(word_row)
        emit = np.asarray(emit)
        for i in np.nonzero(emit)[0]:
            results[int(word_row[i])][bytes(cand[i, : cand_len[i]])] += 1
    return results


def assert_parity(sub_map, words, min_sub=0, max_sub=15, *, reverse=False):
    got = run_device(sub_map, words, min_sub, max_sub, reverse=reverse)
    for i, word in enumerate(words):
        if reverse:
            want = Counter(
                process_word_reverse(
                    word, sub_map, min_sub, max_sub, bug_compat=False
                )
            )
        else:
            want = Counter(process_word(word, sub_map, min_sub, max_sub))
        assert got[i] == want, (word, min_sub, max_sub, reverse)


# --------------------------------------------------------------------------
# Default mode
# --------------------------------------------------------------------------


class TestDefaultMode:
    def test_q10_keyspace_shape(self):
        # 'password': all 8 byte positions substitutable, one option each ->
        # 2^8 - 1 = 255; 'hello' -> 31 (SURVEY.md Q10 verified vectors).
        sub_map = {bytes([c]): [bytes([c]).upper()] for c in b"pasword"}
        got = run_device(sub_map, [b"password"], 0, 15)
        assert sum(got[0].values()) == 255
        sub_map2 = {c: [c.upper()] for c in [b"h", b"e", b"l", b"o"]}
        got2 = run_device(sub_map2, [b"hello"], 0, 15)
        assert sum(got2[0].values()) == 31

    def test_q1_original_never_emitted(self):
        sub_map = {b"a": [b"4"]}
        got = run_device(sub_map, [b"aa"], 0, 15)
        assert b"aa" not in got[0]
        assert_parity(sub_map, [b"aa", b"b", b""], 0, 15)

    def test_q5_longest_first_multiset(self):
        # 'ss' with {s=Z, ss=ß}: oracle multiset {ß, Zs, ZZ, sZ}.
        sub_map = {b"s": [b"Z"], b"ss": [b"\xc3\x9f"]}
        got = run_device(sub_map, [b"ss"], 0, 15)
        assert got[0] == Counter([b"\xc3\x9f", b"Zs", b"ZZ", b"sZ"])
        assert_parity(sub_map, [b"ss", b"sss", b"ssss", b"s", b"xsx"])

    def test_q6_no_rematch_of_replacement(self):
        # 'ab' with a=b, b=c: {bb, bc, ac} and never 'cc'.
        sub_map = {b"a": [b"b"], b"b": [b"c"]}
        got = run_device(sub_map, [b"ab"], 0, 15)
        assert got[0] == Counter([b"bb", b"bc", b"ac"])

    def test_q7_convergent_paths_duplicate(self):
        # 'ab' with a=X, ab=Xb -> Xb twice.
        sub_map = {b"a": [b"X"], b"ab": [b"Xb"]}
        got = run_device(sub_map, [b"ab"], 0, 15)
        assert got[0] == Counter({b"Xb": 2})
        assert_parity(sub_map, [b"ab", b"abab"])

    def test_q7_duplicate_options(self):
        sub_map = {b"a": [b"X", b"X"]}
        got = run_device(sub_map, [b"za"], 0, 15)
        assert got[0] == Counter({b"zX": 2})

    def test_multi_option_parity(self):
        sub_map = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5", b"z"]}
        assert_parity(sub_map, [b"aos", b"ssaa", b"xyz", b""])

    def test_min_max_windows(self):
        sub_map = {b"a": [b"4"], b"o": [b"0"], b"s": [b"$"], b"e": [b"3"]}
        words = [b"aoese", b"sea", b"x"]
        for mn, mx in [(0, 15), (1, 2), (2, 2), (3, 3), (0, 0), (2, 1), (4, 9)]:
            assert_parity(sub_map, words, mn, mx)

    def test_length_changing_values(self):
        sub_map = {b"s": [b"\xc3\x9f", b""], b"e": [b"\xd0\xad"]}
        assert_parity(sub_map, [b"sees", b"s", b"esse"])

    def test_overlapping_multichar_keys(self):
        # 's', 'ss', 'sss' all present: heavy interval overlap, no fallback.
        sub_map = {b"s": [b"1"], b"ss": [b"22"], b"sss": [b"333", b"x"]}
        assert_parity(sub_map, [b"sssss", b"ss", b"s"])

    def test_empty_key_inert(self):
        # A '=x' table line: match length >= 1 means it can never fire.
        sub_map = {b"": [b"!"], b"a": [b"4"]}
        assert_parity(sub_map, [b"ab", b""])

    @pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
    def test_builtin_table_parity(self, name):
        sub_map = BUILTIN_LAYOUTS[name].to_substitution_map()
        words = [b"pass", b"hi", b"", b"a", "λόγος".encode(), b"Pa,s"]
        assert_parity(sub_map, words, 0, 15)

    @pytest.mark.slow  # ~9 s on the tier-1 host; block splitting keeps
    # default coverage via the multi-block suball parity test in
    # test_pallas_expand and the strided CLI arm.
    def test_block_splitting_matches_whole_run(self):
        sub_map = {b"a": [b"1", b"2", b"3"], b"b": [b"x", b"y"], b"c": [b"q"]}
        words = [b"abcabc", b"cab"]
        small = run_device(sub_map, words, 0, 15, lanes=7)
        big = run_device(sub_map, words, 0, 15, lanes=4096)
        assert small == big


# --------------------------------------------------------------------------
# Reverse mode
# --------------------------------------------------------------------------


class TestReverseMode:
    def test_q1_original_emitted_at_min_zero(self):
        sub_map = {b"a": [b"4"]}
        got = run_device(sub_map, [b"aa", b"zz"], 0, 15, reverse=True)
        assert got[0][b"aa"] == 1
        assert got[1] == Counter({b"zz": 1})

    def test_q2_first_option_only(self):
        sub_map = {b"a": [b"4", b"@"], b"b": [b"8", b"6", b"&"]}
        got = run_device(sub_map, [b"ab"], 1, 15, reverse=True)
        assert got[0] == Counter([b"4b", b"a8", b"48"])
        assert_parity(sub_map, [b"ab", b"aabb"], 0, 15, reverse=True)

    def test_q3_corrected_offsets_length_changing(self):
        # 'ab' with a=XX, b=YY at exactly 2 subs: the buggy Go binary emits
        # 'aXXY'; the engine proper (== oracle bug_compat=False) emits 'XXYY'.
        sub_map = {b"a": [b"XX"], b"b": [b"YY"]}
        got = run_device(sub_map, [b"ab"], 2, 2, reverse=True)
        assert got[0] == Counter([b"XXYY"])
        assert_parity(sub_map, [b"ab", b"ba", b"abab"], 0, 15, reverse=True)

    def test_overlap_filter(self):
        # 'ab' and 'b' overlap in 'ab': combos containing both are rejected.
        sub_map = {b"ab": [b"X"], b"b": [b"Y"]}
        assert_parity(sub_map, [b"ab", b"aab", b"abb"], 0, 15, reverse=True)

    def test_min_max_windows(self):
        sub_map = {b"a": [b"4"], b"o": [b"0"], b"s": [b"$"]}
        words = [b"aos", b"sa", b"q"]
        for mn, mx in [(0, 15), (1, 1), (2, 2), (0, 0), (3, 3), (2, 1)]:
            assert_parity(sub_map, words, mn, mx, reverse=True)

    @pytest.mark.parametrize("name", sorted(BUILTIN_LAYOUTS))
    def test_builtin_table_parity(self, name):
        sub_map = BUILTIN_LAYOUTS[name].to_substitution_map()
        words = [b"pass", b"hi", b"", b"Pa,s"]
        assert_parity(sub_map, words, 0, 15, reverse=True)


class TestFixedStride:
    """The TPU-fast fixed-stride block layout (arithmetic lane -> block,
    per-block broadcasts) must emit exactly the multiset the packed
    variable-offset layout emits."""

    LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$"], b"e": [b"3"]}
    WORDS = [b"password", b"sesame", b"a", b"zzz", b"assesses", b"oboe"]

    def test_block_layout_invariants(self):
        ct = compile_table(self.LEET)
        plan = build_match_plan(ct, pack_words(self.WORDS))
        batch, w, rank = make_blocks(
            plan, max_variants=256, max_blocks=32, fixed_stride=8
        )
        assert list(batch.offset) == [8 * i for i in range(len(batch.count))]
        assert all(c <= 8 for c in batch.count)
        # Lane budget, not variant budget: at most 256/8 = 32 blocks.
        assert len(batch.count) <= 32

    def test_stride_multiset_matches_oracle(self):
        lanes, stride = 512, 16
        ct = compile_table(self.LEET)
        packed = pack_words(self.WORDS)
        plan = build_match_plan(ct, packed)
        results = {i: Counter() for i in range(len(self.WORDS))}
        w = rank = 0
        while True:
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=lanes,
                max_blocks=lanes // stride, fixed_stride=stride,
            )
            if batch.total == 0:
                break
            from hashcat_a5_table_generator_tpu.ops.blocks import pad_batch

            batch = pad_batch(batch, lanes // stride)
            cand, cand_len, word_row, emit = expand_matches(
                jnp.asarray(plan.tokens),
                jnp.asarray(plan.lengths),
                jnp.asarray(plan.match_pos),
                jnp.asarray(plan.match_len),
                jnp.asarray(plan.match_radix),
                jnp.asarray(plan.match_val_start),
                jnp.asarray(ct.val_bytes),
                jnp.asarray(ct.val_len),
                jnp.asarray(batch.word),
                jnp.asarray(batch.base_digits),
                jnp.asarray(batch.count),
                jnp.asarray(batch.offset),
                num_lanes=lanes,
                out_width=plan.out_width,
                min_substitute=1,
                max_substitute=15,
                block_stride=stride,
            )
            cand, cand_len = np.asarray(cand), np.asarray(cand_len)
            word_row, emit = np.asarray(word_row), np.asarray(emit)
            for i in np.nonzero(emit)[0]:
                results[int(word_row[i])][
                    bytes(cand[i, : cand_len[i]])
                ] += 1
        for i, word in enumerate(self.WORDS):
            want = Counter(process_word(word, self.LEET, 1, 15))
            assert results[i] == want, word

    def test_stride_sweep_stream_identical_to_packed(self):
        # Full runtime equality: same candidate BYTES in the same order.
        import io

        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.runtime.sinks import (
            CandidateWriter,
        )
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        spec = AttackSpec(mode="default", algo="md5")
        outs = []
        for packed_blocks in (False, True):
            buf = io.BytesIO()
            cfg = SweepConfig(lanes=64, num_blocks=16,
                              packed_blocks=packed_blocks)
            assert (cfg.resolve_block_stride() is None) == packed_blocks
            with CandidateWriter(stream=buf) as writer:
                Sweep(spec, self.LEET, self.WORDS, config=cfg).run_candidates(
                    writer, resume=False
                )
            outs.append(buf.getvalue())
        assert outs[0] == outs[1]
        assert outs[0]  # non-empty


class TestSpliceImplEquivalence:
    """The CPU (scatter/searchsorted) and TPU (compare-loop) splice
    formulations must be bit-identical on every output — the backend picks
    one at trace time, so a divergence would be an invisible parity split."""

    @pytest.mark.parametrize("table,words", [
        ({b"a": [b"4", b"@"], b"s": [b"$"], b"ss": [b"\xc3\x9f"]},
         [b"assesses", b"a", b"ss", b"zzz"]),
        ({b"e": [b"33"], b"l": [b"1"], b"o": [b"0", b"()"]},
         [b"hello", b"loole", b"x"]),
    ])
    def test_outputs_identical(self, table, words):
        ct = compile_table(table)
        packed = pack_words(words)
        plan = build_match_plan(ct, packed)
        batch, _, _ = make_blocks(plan, max_variants=256, max_blocks=64,
                                  fixed_stride=4)
        from hashcat_a5_table_generator_tpu.ops.blocks import pad_batch

        batch = pad_batch(batch, 64)
        args = (
            jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
            jnp.asarray(plan.match_pos), jnp.asarray(plan.match_len),
            jnp.asarray(plan.match_radix), jnp.asarray(plan.match_val_start),
            jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
            jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
            jnp.asarray(batch.count), jnp.asarray(batch.offset),
        )
        kw = dict(num_lanes=256, out_width=plan.out_width,
                  min_substitute=1, max_substitute=15, block_stride=4)
        a = expand_matches(*args, splice_impl="compare", **kw)
        b = expand_matches(*args, splice_impl="scatter", **kw)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestWindowedEnumeration:
    """Count-windowed enumeration (VERDICT r3 #4): tight -m/-x windows must
    enumerate only in-window digit vectors instead of masking the full
    mixed-radix space."""

    UPPER = {bytes([c]): [bytes([c - 32])]
             for c in range(ord("a"), ord("z") + 1)}
    WORD20 = b"abcdefghijklmnopqrst"  # 20 single-option matches

    def _sweep_counter(self, spec, sub, words, lanes=64, blocks=16):
        import io

        from hashcat_a5_table_generator_tpu.runtime.sinks import (
            CandidateWriter,
        )
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        buf = io.BytesIO()
        sweep = Sweep(spec, sub, words,
                      config=SweepConfig(lanes=lanes, num_blocks=blocks))
        with CandidateWriter(stream=buf) as writer:
            sweep.run_candidates(writer, resume=False)
        return sweep, Counter(buf.getvalue().splitlines())

    def test_lane_efficiency_floor(self):
        # -m 1 -x 1 on a 20-match word: the plan must budget 20 ranks, not
        # 2^20 masked lanes — emitted/enumerated >= 1 (every rank emits).
        from hashcat_a5_table_generator_tpu.models.attack import (
            AttackSpec,
            build_plan,
        )

        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=1)
        plan = build_plan(spec, compile_table(self.UPPER),
                          pack_words([self.WORD20]))
        assert plan.windowed
        assert plan.n_variants == (20,)  # == emitted candidates exactly

    @pytest.mark.parametrize("mn,mx", [
        (1, 1),
        # Each arm is a full sweep+compile (~11 s on the tier-1 host);
        # the (1,1) arm keeps the windowed-oracle multiset parity in
        # the default tier, the wider windows ride CI's slow steps
        # (the windowed decode itself stays default-covered by
        # test_windowed_reverse_mode / test_windowed_crack_hits_decode
        # and the Pallas windowed parity tests).
        pytest.param(0, 2, marks=pytest.mark.slow),
        pytest.param(2, 3, marks=pytest.mark.slow),
        pytest.param(1, 4, marks=pytest.mark.slow),
    ])
    def test_windowed_multiset_parity_across_windows(self, mn, mx):
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec

        words = [self.WORD20, b"zz", b"abc", b"aaaa"]
        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=mn, max_substitute=mx)
        sweep, got = self._sweep_counter(spec, self.UPPER, words)
        assert sweep.plan.windowed, (mn, mx)
        want = Counter()
        for w in words:
            want.update(iter_candidates(w, self.UPPER, mn, mx))
        assert got == want, (mn, mx)

    def test_windowed_reverse_mode(self):
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec

        leet = {b"a": [b"4", b"@"], b"s": [b"$", b"5"], b"e": [b"3"]}
        words = [b"assesses", b"sea", b"xyz"]
        spec = AttackSpec(mode="reverse", algo="md5",
                          min_substitute=0, max_substitute=2)
        sweep, got = self._sweep_counter(spec, leet, words)
        assert sweep.plan.windowed
        want = Counter()
        for w in words:
            want.update(
                iter_candidates(w, leet, 0, 2, reverse=True)
            )
        assert got == want

    @pytest.mark.slow  # ~13 s on the tier-1 host; windowed hit decode
    # keeps default coverage via the windowed parity tests in
    # test_pallas_expand and the windowed pack arm.
    def test_windowed_crack_hits_decode(self):
        # decode_variant + lane_cursor must invert the windowed ranks: a
        # planted digest's hit candidate must reconstruct exactly.
        import hashlib

        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        spec = AttackSpec(mode="default", algo="md5",
                          min_substitute=1, max_substitute=2)
        words = [self.WORD20, b"abc"]
        cands = list(iter_candidates(self.WORD20, self.UPPER, 1, 2))
        planted = [cands[0], cands[len(cands) // 2], cands[-1],
                   next(iter_candidates(b"abc", self.UPPER, 1, 2))]
        digests = [hashlib.md5(c).digest() for c in planted]
        sweep = Sweep(spec, self.UPPER, words, digests,
                      config=SweepConfig(lanes=64, num_blocks=16))
        assert sweep.plan.windowed
        res = sweep.run_crack(resume=False)
        assert sorted(h.candidate for h in res.hits) == sorted(planted)

    def test_wide_window_stays_full_enumeration(self):
        # The default -x 15 window is not windowed-eligible (K > 8) — the
        # bench/headline path must keep the carry-decode scheme.
        from hashcat_a5_table_generator_tpu.models.attack import (
            AttackSpec,
            build_plan,
        )

        spec = AttackSpec(mode="default", algo="md5")
        plan = build_plan(spec, compile_table(self.UPPER),
                          pack_words([self.WORD20]))
        assert not plan.windowed
        assert plan.n_variants == (2 ** 20,)

    def test_windowed_suball_modes(self):
        # Eight single-option patterns per word: full space 2^8 = 256 per
        # word vs ~37 windowed ranks — comfortably past the 2x gain gate.
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec

        leet = {k.encode(): [k.upper().encode()]
                for k in "asetonir"}
        words = [b"administrations", b"penetrations", b"xyz", b"oooo"]
        for mode, rev in [("suball", False), ("suball-reverse", True)]:
            spec = AttackSpec(mode=mode, algo="md5",
                              min_substitute=1, max_substitute=2)
            sweep, got = self._sweep_counter(spec, leet, words)
            assert sweep.plan.windowed, mode
            want = Counter()
            for w in words:
                want.update(
                    iter_candidates(w, leet, 1, 2, substitute_all=True,
                                    reverse=rev)
                )
            assert got == want, mode

    def test_windowed_suball_fallback_words_keep_oracle_route(self):
        # Cascade-hazard words must stay oracle-routed under windowed
        # enumeration (total 0 -> device never cuts blocks for them). The
        # fixture mixes hazard words with 8-pattern words so the windowed
        # gain gate genuinely engages.
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec

        sub = {k.encode(): [k.upper().encode()] for k in "setonird"}
        # Boundary-CROSSING hazard (the inserted 'c' can extend into a new
        # 'cb' match): genuinely pathological, so it stays oracle-routed
        # even with cascade closure.
        sub[b"a"] = [b"c"]
        sub[b"cb"] = [b"Z"]
        words = [b"acb", b"considerations", b"cba", b"introductions"]
        spec = AttackSpec(mode="suball", algo="md5",
                          min_substitute=0, max_substitute=2)
        sweep, got = self._sweep_counter(spec, sub, words)
        assert sweep.plan.windowed  # the gate engaged — no dead assertions
        assert sweep.fallback_rows  # and hazard words exist alongside
        for row in sweep.fallback_rows:
            assert sweep.plan.n_variants[row] == 0
        want = Counter()
        for w in words:
            want.update(iter_candidates(w, sub, 0, 2, substitute_all=True))
        assert got == want

    def test_windowed_checkpoint_fingerprint_distinct(self, tmp_path):
        # Same inputs, different enumeration schemes (via the eligibility
        # rule) must never share a fingerprint token — guard the cursor
        # renumbering.
        from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
        from hashcat_a5_table_generator_tpu.runtime.sweep import (
            Sweep,
            SweepConfig,
        )

        cfg = SweepConfig(lanes=64, num_blocks=16)
        tight = Sweep(
            AttackSpec(mode="default", algo="md5", min_substitute=1,
                       max_substitute=1),
            self.UPPER, [self.WORD20], config=cfg,
        )
        wide = Sweep(
            AttackSpec(mode="default", algo="md5"),
            self.UPPER, [self.WORD20], config=cfg,
        )
        assert tight.plan.windowed and not wide.plan.windowed
        assert tight.fingerprint != wide.fingerprint


def test_find_matches_scan_order():
    ct = compile_table({b"s": [b"1"], b"ss": [b"2"]})
    # position ascending, key length descending at each position.
    assert [(p, l) for p, l, _ in find_matches(b"ss", ct)] == [
        (0, 2),
        (0, 1),
        (1, 1),
    ]


class TestBatchMatchScan:
    """The vectorized batch scan inside build_match_plan must reproduce the
    per-word find_matches construction exactly — every plan field, variant
    total (including bigint rows), and the derived out_width."""

    TABLES = [
        {b"s": [b"1"], b"ss": [b"2"]},  # overlapping multi-char key
        {b"a": [b"\xc3\xa4"], b"ss": [b"\xc3\x9f"], b"u": []},  # 0-option key
        {bytes([c]): [b"x", b"yy", b"z"] for c in b"abcdefgh"},  # 3 options
    ]
    WORDS = [b"", b"s", b"ss", b"sss", b"glass", b"strasse", b"aaaa",
             b"abcdefgh" * 4, b"zzz", b"au", b"x" * 30]

    @pytest.mark.parametrize("first_option_only", [False, True])
    @pytest.mark.parametrize("table_idx", range(len(TABLES)))
    def test_matches_scalar_reference(self, table_idx, first_option_only):
        ct = compile_table(self.TABLES[table_idx])
        packed = pack_words(self.WORDS)
        plan = build_match_plan(
            ct, packed, first_option_only=first_option_only
        )
        # Scalar reference reconstruction (the pre-vectorization loop).
        b = packed.batch
        per_word = [find_matches(packed.word(i), ct) for i in range(b)]
        m = max(1, max((len(x) for x in per_word), default=0))
        assert plan.num_slots == m
        for i, matches in enumerate(per_word):
            total = 1
            for s, (pos, klen, ki) in enumerate(matches):
                vc = int(ct.val_count[ki])
                radix = 2 if first_option_only else vc + 1
                if vc == 0:
                    radix = 1
                assert plan.match_pos[i, s] == pos
                assert plan.match_len[i, s] == klen
                assert plan.match_radix[i, s] == radix
                assert plan.match_val_start[i, s] == ct.val_start[ki]
                total *= radix
            for s in range(len(matches), m):
                assert plan.match_radix[i, s] == 1
                assert plan.match_len[i, s] == 0
            assert plan.n_variants[i] == total

    def test_bigint_variant_totals(self):
        # 40 positions x radix 4 = 4^40 > 2^63: the exact-recompute path.
        ct = compile_table({b"a": [b"x", b"y", b"z"]})
        packed = pack_words([b"a" * 40, b"aa"])
        plan = build_match_plan(ct, packed)
        assert plan.n_variants[0] == 4 ** 40
        assert plan.n_variants[1] == 16

    def test_key_longer_than_packed_width(self):
        # A key longer than the widest dictionary word can never match;
        # the batch scan must return the empty-match plan, not crash
        # (regression: negative shifted-compare slices).
        ct = compile_table({b"abcdefgh": [b"X"], b"a": [b"4"]})
        packed = pack_words([b"ab", b"a"])
        plan = build_match_plan(ct, packed)
        ref = [find_matches(packed.word(i), ct) for i in range(2)]
        assert [len(r) for r in ref] == [1, 1]  # only the 1-byte key
        assert plan.n_variants == (2, 2)
        assert (plan.match_len[:, 0] == 1).all()


class TestRadix2Decode:
    """The K=1 bit-extraction decode (``decode_digits(radix2=True)``) must
    be lane-for-lane identical to the general decode on radix-<=2 plans —
    match and suball, fixed-stride and packed layouts."""

    SUB = {b"a": [b"4"], b"e": [b"3"], b"s": [b"$"], b"o": [b"0"],
           b"ss": [b"\xc3\x9f"]}
    WORDS = [b"glasses", b"x", b"", b"assess", b"aeoaeo", b"mississippi"]

    def _match_args(self, stride):
        ct = compile_table(self.SUB)
        packed = pack_words(self.WORDS)
        plan = build_match_plan(ct, packed)
        lanes = 512
        outs = []
        w = rank = 0
        while True:
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=lanes,
                max_blocks=lanes // (stride or 64),
                fixed_stride=stride,
            )
            if batch.total == 0:
                break
            if stride is not None:
                from hashcat_a5_table_generator_tpu.ops.blocks import (
                    pad_batch,
                )

                batch = pad_batch(batch, lanes // stride)
            outs.append((plan, ct, batch))
        assert outs
        return lanes, outs

    @pytest.mark.parametrize("stride", [64, None])
    def test_match_radix2_identical(self, stride):
        lanes, launches = self._match_args(stride)
        for plan, ct, batch in launches:
            args = (
                jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
                jnp.asarray(plan.match_pos), jnp.asarray(plan.match_len),
                jnp.asarray(plan.match_radix),
                jnp.asarray(plan.match_val_start),
                jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
                jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
                jnp.asarray(batch.count), jnp.asarray(batch.offset),
            )
            kw = dict(num_lanes=lanes, out_width=plan.out_width,
                      min_substitute=1, max_substitute=15,
                      block_stride=stride)
            a = expand_matches(*args, radix2=False, **kw)
            b = expand_matches(*args, radix2=True, **kw)
            for x, y in zip(a, b):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_suball_radix2_identical(self):
        from hashcat_a5_table_generator_tpu.ops.expand_suball import (
            build_suball_plan,
            expand_suball,
        )

        ct = compile_table(self.SUB)
        packed = pack_words(self.WORDS)
        plan = build_suball_plan(ct, packed)
        lanes = 256
        w = rank = 0
        saw = False
        while True:
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=lanes,
                max_blocks=4, fixed_stride=64,
            )
            if batch.total == 0:
                break
            saw = True
            from hashcat_a5_table_generator_tpu.ops.blocks import pad_batch

            batch = pad_batch(batch, 4)
            args = (
                jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
                jnp.asarray(plan.pat_radix),
                jnp.asarray(plan.pat_val_start),
                jnp.asarray(plan.seg_orig_start),
                jnp.asarray(plan.seg_orig_len), jnp.asarray(plan.seg_pat),
                jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
                jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
                jnp.asarray(batch.count), jnp.asarray(batch.offset),
            )
            kw = dict(num_lanes=lanes, out_width=plan.out_width,
                      min_substitute=1, max_substitute=15, block_stride=64)
            a = expand_suball(*args, radix2=False, **kw)
            b = expand_suball(*args, radix2=True, **kw)
            for x, y in zip(a, b):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert saw

"""graftlint rule corpus: every rule must both FLAG its hazard and stay
quiet on the idiomatic alternative.  Fixture snippets live in
tests/lint_fixtures/ as ``<code>_flag.py`` / ``<code>_ok.py`` pairs,
each declaring the virtual package path it is linted under (rules are
path-scoped: ops/ dtype rules, library stdout rules, ...)."""

import pathlib
import re
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint import (  # noqa: E402
    ALL_RULES,
    lint_paths,
    lint_source,
)

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent / "lint_fixtures"
CODES = [rule.code for rule in ALL_RULES]

_VPATH_RE = re.compile(r"#\s*graftlint-virtual-path:\s*(\S+)")


def _load_fixture(code: str, kind: str):
    path = FIXTURE_DIR / f"{code.lower()}_{kind}.py"
    source = path.read_text(encoding="utf-8")
    match = _VPATH_RE.search(source)
    assert match, f"{path.name} must declare # graftlint-virtual-path:"
    return source, match.group(1)


def test_issue_floor_of_eight_rules():
    """The tentpole contract: >= 8 repo-specific rules, stable codes."""
    assert len(ALL_RULES) >= 8
    assert len(set(CODES)) == len(CODES), "duplicate rule codes"
    for rule in ALL_RULES:
        assert re.fullmatch(r"GL\d{3}", rule.code)
        assert rule.name and rule.summary and rule.rationale


@pytest.mark.parametrize("code", CODES)
def test_rule_flags_its_hazard(code):
    source, vpath = _load_fixture(code, "flag")
    findings = lint_source(source, vpath, select=[code])
    assert findings, f"{code} did not flag its hazard fixture"
    assert all(f.code == code for f in findings)
    assert all(f.path == vpath for f in findings)


@pytest.mark.parametrize("code", CODES)
def test_rule_passes_the_idiom(code):
    source, vpath = _load_fixture(code, "ok")
    findings = lint_source(source, vpath, select=[code])
    assert not findings, (
        f"{code} false-positived on its ok fixture: "
        + "; ".join(f.render() for f in findings)
    )


@pytest.mark.parametrize("code", CODES)
def test_fixture_pair_exists(code):
    for kind in ("flag", "ok"):
        assert (FIXTURE_DIR / f"{code.lower()}_{kind}.py").is_file()


def test_suppression_comment_silences_one_line():
    source, vpath = _load_fixture("GL001", "flag")
    suppressed = "\n".join(
        line + "  # graftlint: disable=GL001"
        if not line.lstrip().startswith("#") else line
        for line in source.splitlines()
    )
    assert not lint_source(suppressed, vpath, select=["GL001"])


def test_path_scoping_gates_ops_rules():
    """The same hazard outside ops/ is out of scope for ops-only rules."""
    source, _ = _load_fixture("GL001", "flag")
    outside = "hashcat_a5_table_generator_tpu/runtime/_fixture.py"
    assert not lint_source(source, outside, select=["GL001"])


def test_select_unknown_code_raises():
    with pytest.raises(ValueError, match="unknown rule code"):
        lint_source("x = 1\n", "m.py", select=["GL999"])


def test_repo_is_clean():
    """The acceptance gate scripts/lint.sh enforces, as a test: the
    shipped package must lint clean."""
    findings = lint_paths(
        [
            str(REPO_ROOT / "hashcat_a5_table_generator_tpu"),
            str(REPO_ROOT / "tools"),
        ]
    )
    assert not findings, "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
    """0 on clean, 1 on findings, 2 on unknown rule code."""
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    env_cwd = str(REPO_ROOT)
    runs = {
        0: [sys.executable, "-m", "tools.graftlint", str(clean)],
        2: [
            sys.executable, "-m", "tools.graftlint",
            "--select", "GL999", str(clean),
        ],
    }
    for expected, cmd in runs.items():
        proc = subprocess.run(
            cmd, cwd=env_cwd, capture_output=True, text=True, timeout=120
        )
        assert proc.returncode == expected, proc.stderr
    dirty = tmp_path / "hashcat_a5_table_generator_tpu" / "ops"
    dirty.mkdir(parents=True)
    (dirty / "bad.py").write_text("WIDE = 0x1FFFFFFFF\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(tmp_path)],
        cwd=env_cwd, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "GL001" in proc.stdout

"""Interpret-mode parity for the fused Pallas expand+MD5 kernel
(``ops.pallas_expand``): for every EMITTED lane the MD5 state must match
the XLA ``expand_matches`` + ``ops.hashes.md5`` pair bit-for-bit, and the
emit mask itself must be identical — the kernel replaces both stages in the
production crack step, so any divergence is silent candidate loss."""

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec, build_plan
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.expand_matches import expand_matches
from hashcat_a5_table_generator_tpu.ops.hashes import HASH_FNS
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
    eligible,
    fused_expand_md5,
    k_opts_for,
    opts_for,
)
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

LEET = {
    b"a": [b"4", b"@"],
    b"e": [b"3"],
    b"l": [b"1", b"|"],
    b"o": [b"0"],
    b"s": [b"5", b"$"],
    b"ss": [b"\xc3\x9f"],
}
#: Deliberately small: interpret-mode kernel cost scales with total
#: variants. Coverage kept: empty/1-char words, multi-match words, the
#: multi-char-key path, and (via assassin's ~3k variants at 1024-lane
#: launches) multi-block words with nonzero base digits AND multi-launch
#: sweeps.
WORDS = [b"glass", b"x", b"", b"hello", b"assassin", b"misses"]

STRIDE = 128


def _arrays(spec, words=WORDS, sub=LEET):
    ct = compile_table(sub)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    return ct, plan


def _sweep_both(spec, plan, ct, plan_fields, xla_fn, fused_fn, *,
                num_blocks=8, algo="md5", **fused_kw):
    """Shared full-space sweep harness: run every launch through the XLA
    expand+md5 pair AND the fused kernel; returns per-launch
    (emit_xla, emit_pal, state_xla, state_pal). ``plan_fields`` names the
    plan attributes forming the mode's arg tuple (candidate/table arrays
    appended)."""
    import jax.numpy as jnp

    from hashcat_a5_table_generator_tpu.ops.pallas_expand import k_vals_for

    lanes = num_blocks * STRIDE
    k_opts = k_vals_for(plan)
    w = rank = 0
    outs = []
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=lanes,
            max_blocks=num_blocks, fixed_stride=STRIDE,
        )
        if batch.total == 0:
            break
        batch = pad_batch(batch, num_blocks)
        # Cascade-closed plans carry their own value table + joint-index
        # fields (exactly what models.attack wires in production).
        vb = ct.val_bytes if getattr(plan, "cval_bytes", None) is None \
            else plan.cval_bytes
        vl = ct.val_len if getattr(plan, "cval_len", None) is None \
            else plan.cval_len
        close_kw = {}
        if getattr(plan, "close_next", None) is not None:
            close_kw = dict(close_next=jnp.asarray(plan.close_next),
                            close_mul=jnp.asarray(plan.close_mul))
        args = tuple(
            jnp.asarray(getattr(plan, f)) for f in plan_fields
        ) + (jnp.asarray(vb), jnp.asarray(vl))
        blocks = (
            jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
            jnp.asarray(batch.count), jnp.asarray(batch.offset),
        )
        common = dict(
            num_lanes=lanes, out_width=plan.out_width,
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute,
            block_stride=STRIDE,
        )
        if getattr(plan, "windowed", False):
            # Both paths take the same suffix-count DP table.
            common["win_v"] = jnp.asarray(plan.win_v)
        cand, clen, _, emit_x = xla_fn(*args, *blocks, **common, **close_kw)
        state_x = HASH_FNS[algo](cand, clen)
        state_p, emit_p = fused_fn(
            *args, blocks[0], blocks[1], blocks[2],
            k_opts=k_opts, algo=algo, interpret=True, **common, **close_kw,
            **fused_kw,
        )
        outs.append((
            np.asarray(emit_x), np.asarray(emit_p),
            np.asarray(state_x), np.asarray(state_p),
        ))
    assert outs, "no launches cut"
    return outs


def _run_both(spec, plan, ct, *, num_blocks=8, algo="md5", **fused_kw):
    return _sweep_both(
        spec, plan, ct,
        ("tokens", "lengths", "match_pos", "match_len", "match_radix",
         "match_val_start"),
        expand_matches, fused_expand_md5, num_blocks=num_blocks, algo=algo,
        **fused_kw,
    )


@pytest.mark.parametrize("mode", [
    # The default-mode arm costs ~20 s interpret-mode on the tier-1
    # host; the reverse arm drives the identical single-block kernel
    # path and keeps the family's fast default coverage.
    pytest.param("default", marks=pytest.mark.slow),
    "reverse",
])
def test_state_and_emit_match_xla(mode):
    spec = AttackSpec(mode=mode, algo="md5")
    ct, plan = _arrays(spec)
    for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        assert emit_x.any()  # the comparison must not be vacuous


@pytest.mark.slow  # ~17 s interpret cost on the tier-1 host; the
# in-tile window mask keeps default coverage via the windowed parity
# tests below and the emit-scheme window fuzz arm.
def test_count_window_respected():
    # max_substitute > WINDOWED_MAX_SUBST keeps the plan on FULL
    # enumeration (the windowed decode has its own parity tests below),
    # while min_substitute still prunes low-count lanes — the kernel's
    # in-tile window mask must agree exactly.
    spec = AttackSpec(mode="default", algo="md5", min_substitute=2,
                      max_substitute=9)
    ct, plan = _arrays(spec)
    assert not plan.windowed
    saw_emit = False
    for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        saw_emit = saw_emit or emit_x.any()
    assert saw_emit


def test_multibyte_values_and_multichar_keys():
    # german-style: multi-char key (ss) and 2-byte UTF-8 values.
    sub = {b"a": [b"\xc3\xa4"], b"o": [b"\xc3\xb6"], b"u": [b"\xc3\xbc"],
           b"ss": [b"\xc3\x9f"], b"s": [b"z", b"Z"]}
    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(sub)
    packed = pack_words([b"strasse", b"gauss", b"umlaut", b"sos"])
    plan = build_plan(spec, ct, packed)
    for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        assert emit_x.any()


def test_opts_for_gates(monkeypatch):
    import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

    spec = AttackSpec(mode="default", algo="md5")
    ct, plan = _arrays(spec)
    monkeypatch.delenv("A5GEN_PALLAS", raising=False)
    # CPU CI: the platform gate must keep the kernel off even though the
    # kernel is default-on (env unset)...
    assert opts_for(spec, plan, ct, block_stride=128, num_blocks=16) is None

    # ...and with a (faked) TPU device the full gate opens by default.
    class _Dev:
        platform = "tpu"

    monkeypatch.setattr(pe.jax, "devices", lambda: [_Dev()])
    assert opts_for(spec, plan, ct, block_stride=128, num_blocks=16) == 2
    # The env var is an opt-OUT now ("expand" still force-opts in; "1"
    # selects the hash-only kernel, which also opts this one out).
    for off in ("off", "0", "xla", "none", "1"):
        monkeypatch.setenv("A5GEN_PALLAS", off)
        assert opts_for(spec, plan, ct,
                        block_stride=128, num_blocks=16) is None
    monkeypatch.setenv("A5GEN_PALLAS", "expand")
    assert opts_for(spec, plan, ct, block_stride=128, num_blocks=16) == 2
    # Ineligible shapes stay off.
    assert opts_for(spec, plan, ct, block_stride=64, num_blocks=16) is None
    assert opts_for(spec, plan, ct, block_stride=None, num_blocks=16) is None
    # The pure-config gate ignores the env entirely.
    monkeypatch.setenv("A5GEN_PALLAS", "off")
    assert pe.opts_for_config(spec, plan, ct, block_stride=128,
                              num_blocks=16, require_tpu=False) == 2


def test_eligible_bounds():
    base = dict(mode="default", algo="md5", windowed=False, block_stride=128,
                num_blocks=16, out_width=40, num_slots=8, token_width=16,
                max_val_len=2, max_options=2)
    assert eligible(**base)
    assert eligible(**{**base, "mode": "suball", "num_segments": 33})
    # Windowed plans are eligible WITH their DP table's column count.
    assert eligible(**{**base, "windowed": True, "win_k2": 3})
    # Multi-block widening: out_width up to 3 chained hash blocks.
    assert eligible(**{**base, "out_width": 119})
    assert eligible(**{**base, "out_width": 183})
    assert eligible(**{**base, "algo": "ntlm", "out_width": 91})
    for bad in (
        dict(mode="plain"), dict(algo="sha256"),
        dict(windowed=True),  # windowed without win_k2: no DP table
        dict(windowed=True, win_k2=11),
        dict(block_stride=96), dict(num_blocks=12), dict(out_width=184),
        dict(algo="ntlm", out_width=92),
        dict(max_val_len=5), dict(max_options=13), dict(token_width=65),
        dict(num_segments=65),
    ):
        assert not eligible(**{**base, **bad}), bad


def _run_both_suball(spec, plan, ct, *, num_blocks=8, algo="md5",
                     **fused_kw):
    from hashcat_a5_table_generator_tpu.ops.expand_suball import expand_suball
    from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
        fused_expand_suball_md5,
    )

    return _sweep_both(
        spec, plan, ct,
        ("tokens", "lengths", "pat_radix", "pat_val_start",
         "seg_orig_start", "seg_orig_len", "seg_pat"),
        expand_suball, fused_expand_suball_md5, num_blocks=num_blocks,
        algo=algo, **fused_kw,
    )


#: Suball tests need a table with no overlapping keys: LEET's s/ss pair
#: claims overlapping spans, routing those words through the oracle, and
#: fallback words never reach any device kernel.
SUBALL_TABLE = {
    b"a": [b"4", b"@"],
    b"e": [b"3"],
    b"l": [b"1", b"|"],
    b"o": [b"0"],
    b"s": [b"5", b"$"],
}


@pytest.mark.parametrize("mode", ["suball", "suball-reverse"])
def test_suball_state_and_emit_match_xla(mode):
    spec = AttackSpec(mode=mode, algo="md5")
    ct, plan = _arrays(spec, sub=SUBALL_TABLE)
    assert not plan.fallback.any()
    saw = False
    for emit_x, emit_p, state_x, state_p in _run_both_suball(spec, plan, ct):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        saw = saw or emit_x.any()
    assert saw


def test_suball_multichar_key_segments():
    # Multi-char patterns produce multi-byte spans: non-start bytes of a
    # chosen segment must contribute nothing, unchosen ones pass through.
    sub = {b"ss": [b"\xc3\x9f"], b"a": [b"4", b"@"], b"e": [b"3"]}
    spec = AttackSpec(mode="suball", algo="md5")
    ct = compile_table(sub)
    packed = pack_words([b"strasse", b"assess", b"sea"])
    plan = build_plan(spec, ct, packed)
    if plan.fallback.any():
        pytest.skip("table routed words to the oracle; kernel never sees them")
    saw = False
    for emit_x, emit_p, state_x, state_p in _run_both_suball(spec, plan, ct):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        saw = saw or emit_x.any()
    assert saw


def test_opts_for_covers_suball(monkeypatch):
    import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

    spec = AttackSpec(mode="suball", algo="md5")
    ct = compile_table(LEET)
    plan = build_plan(spec, ct, pack_words(WORDS))
    monkeypatch.setenv("A5GEN_PALLAS", "expand")

    class _Dev:
        platform = "tpu"

    monkeypatch.setattr(pe.jax, "devices", lambda: [_Dev()])
    assert opts_for(spec, plan, ct, block_stride=128, num_blocks=16) == 2


#: K=1 scalar-units fast path (PERF.md §11): a 1:1 layout-style map (one
#: option per key) with a 2-byte value, exactly the shipped-table shape.
K1_MAP = {b"a": [b"\xd0\xb0"], b"s": [b"5"], b"o": [b"0"], b"l": [b"1"],
          b"e": [b"3"]}


class TestScalarUnits:
    """The K=1 scalar-units kernel (``scalar_units=True``) against the
    XLA pair — the path every shipped 1:1 layout takes in production."""

    @pytest.mark.parametrize("mode,algo,window", [
        ("default", "md5", None), ("reverse", "md5", None),
        ("default", "md5", (2, 9)), ("default", "sha1", None),
        ("default", "ntlm", None),
    ])
    def test_match_parity(self, mode, algo, window):
        kw = dict(mode=mode, algo=algo)
        if window is not None:
            # max > WINDOWED_MAX_SUBST keeps full enumeration; the
            # popcount-based count window must still prune exactly.
            kw.update(min_substitute=window[0], max_substitute=window[1])
        spec = AttackSpec(**kw)
        ct, plan = _arrays(spec, sub=K1_MAP)
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        # Production threads the gate value itself ("single" here —
        # one-byte spans drop the coverage bitmask).
        tier = scalar_units_for(plan)
        assert tier == "single"
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both(
            spec, plan, ct, algo=algo, scalar_units=tier
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    @pytest.mark.parametrize("mode", ["suball", "suball-reverse"])
    def test_suball_parity(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        ct, plan = _arrays(spec, sub=K1_MAP)
        assert not plan.fallback.any()
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both_suball(
            spec, plan, ct, scalar_units=True
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    def test_gate(self):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        # K=2 tables never qualify.
        spec = AttackSpec(mode="default", algo="md5")
        _, plan = _arrays(spec)
        assert not scalar_units_for(plan)
        # K=1 with colliding match starts (s and ss both match at the
        # same position in "assassin"/"misses") must fall back: the
        # packed start encode holds one slot per position.
        k1_collide = {b"s": [b"5"], b"ss": [b"\xc3\x9f"], b"a": [b"4"]}
        ct = compile_table(k1_collide)
        plan = build_plan(spec, ct, pack_words([b"misses", b"sass"]))
        assert k_opts_for(plan) == 1
        assert not scalar_units_for(plan)
        # Only single-byte spans active -> the "single" tier (no
        # coverage bitmask in the kernel).
        plan = build_plan(spec, ct, pack_words([b"banana"]))
        assert scalar_units_for(plan) == "single"
        # Multi-byte spans without collisions -> the bitmask tier.
        ct2 = compile_table({b"ab": [b"X"], b"c": [b"Y"]})
        plan = build_plan(spec, ct2, pack_words([b"cabby"]))
        assert scalar_units_for(plan) is True
        # Suball plans qualify unconditionally (segments are disjoint).
        sspec = AttackSpec(mode="suball", algo="md5")
        ct1 = compile_table(K1_MAP)
        splan = build_plan(sspec, ct1, pack_words([b"glass"]))
        assert scalar_units_for(splan)
        # Windowed plans qualify (the DP decode's bits pack into cb).
        wspec = AttackSpec(mode="default", algo="md5", min_substitute=1,
                           max_substitute=1)
        wplan = build_plan(wspec, ct1, pack_words([b"oleander"]))
        assert wplan.windowed and scalar_units_for(wplan) == "single"

    def test_multichar_key_parity_bitmask_tier(self):
        # K=1 multi-char keys without start collisions take the scalar
        # path WITH the coverage bitmask (scalar_units_for -> True, not
        # "single"): overlap clash masking must match the XLA pair.
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"ab": [b"X"], b"c": [b"YZ"]}
        ct = compile_table(sub)
        plan = build_plan(
            spec, ct, pack_words([b"cabby", b"abcab", b"ccc", b"ab"])
        )
        assert scalar_units_for(plan) is True
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both(
            spec, plan, ct, scalar_units=True
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_windowed_parity(self, mode):
        # Count-windowed plans on the scalar path: the DP decode's chosen
        # bits pack into the same vector, the bitmask unit scheme follows.
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        spec = AttackSpec(mode=mode, algo="md5", min_substitute=1,
                          max_substitute=1)
        ct, plan = _arrays(spec, sub=K1_MAP)
        assert plan.windowed
        tier = scalar_units_for(plan)
        assert tier
        runner = _run_both if mode == "default" else _run_both_suball
        saw = False
        for emit_x, emit_p, state_x, state_p in runner(
            spec, plan, ct, scalar_units=tier
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_pre_fields_match_in_trace_prep(self, mode):
        # scalar_units_fields' numpy precompute (PERF.md §12) must yield
        # bit-identical kernel outputs to the in-trace prep.
        import jax.numpy as jnp

        from hashcat_a5_table_generator_tpu.models.attack import (
            scalar_units_arrays,
        )
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            fused_expand_suball_md5,
            scalar_units_for,
        )

        spec = AttackSpec(mode=mode, algo="md5")
        ct, plan = _arrays(spec, sub=K1_MAP)
        tier = scalar_units_for(plan)
        assert tier
        pre = {k[3:]: v for k, v in scalar_units_arrays(plan, ct).items()}
        suball = mode == "suball"
        fields = (("tokens", "lengths", "pat_radix", "pat_val_start",
                   "seg_orig_start", "seg_orig_len", "seg_pat") if suball
                  else ("tokens", "lengths", "match_pos", "match_len",
                        "match_radix", "match_val_start"))
        fn = fused_expand_suball_md5 if suball else fused_expand_md5
        nb = 8
        batch, _, _ = make_blocks(plan, max_variants=nb * STRIDE,
                                  max_blocks=nb, fixed_stride=STRIDE)
        batch = pad_batch(batch, nb)
        args = tuple(jnp.asarray(getattr(plan, f)) for f in fields) + (
            jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
            jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
            jnp.asarray(batch.count),
        )
        kw = dict(num_lanes=nb * STRIDE, out_width=plan.out_width,
                  min_substitute=spec.effective_min,
                  max_substitute=spec.max_substitute, block_stride=STRIDE,
                  k_opts=1, scalar_units=tier, interpret=True)
        state_a, emit_a = fn(*args, **kw)
        state_b, emit_b = fn(*args, pre=pre, **kw)
        np.testing.assert_array_equal(np.asarray(emit_a),
                                      np.asarray(emit_b))
        np.testing.assert_array_equal(np.asarray(state_a),
                                      np.asarray(state_b))
        assert np.asarray(emit_a).any()

    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_pre_fields_chunking(self, mode):
        # The bounded-memory row chunking must be invisible: tiny chunks
        # produce exactly the full-batch fields.
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_fields,
        )

        spec = AttackSpec(mode=mode, algo="md5")
        ct, plan = _arrays(spec, sub=K1_MAP)
        full = scalar_units_fields(plan, ct)
        tiny = scalar_units_fields(plan, ct, _row_chunk=2)
        assert sorted(full) == sorted(tiny)
        for k in full:
            np.testing.assert_array_equal(full[k], tiny[k])

    @pytest.mark.slow  # ~7 s interpret cost on the tier-1 host; the
    # scalar-unit join keeps default coverage via test_match_parity.
    def test_fuzz_parity(self):
        # Randomized K=1 tables (multichar keys, empty/multibyte values,
        # binary bytes) through whichever tier the gate picks — the bit
        # encodings (packed base, sentinel-31 starts, span bounds) must
        # match the XLA pair on every sample. Few trials: interpret-mode
        # kernel cost dominates.
        import random

        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        rng = random.Random(99)
        alpha = b"abcx\x00\xff"

        def rand_bytes(lo, hi):
            return bytes(rng.choice(alpha) for _ in range(rng.randint(lo, hi)))

        trials = 0
        tiers = set()
        while trials < 3:
            sub = {}
            for _ in range(rng.randint(1, 4)):
                sub[rand_bytes(1, 2)] = [rand_bytes(0, 4)]
            words = [rand_bytes(0, 8) for _ in range(5)]
            spec = AttackSpec(mode="default", algo="md5",
                              min_substitute=rng.choice([0, 1]),
                              max_substitute=15)
            ct = compile_table(sub)
            plan = build_plan(spec, ct, pack_words(words))
            tier = scalar_units_for(plan)
            if not tier or ct.max_val_len < 1:
                continue  # collisions / all-empty values: other tests
            trials += 1
            tiers.add(tier)
            for emit_x, emit_p, state_x, state_p in _run_both(
                spec, plan, ct, scalar_units=tier
            ):
                np.testing.assert_array_equal(emit_x, emit_p)
                np.testing.assert_array_equal(
                    state_x[emit_x], state_p[emit_p]
                )

    def test_collision_table_parity_on_general_path(self):
        # The exact config the gate rejects must still be correct via the
        # general kernel (the wrapper re-checks a bypassed gate and
        # raises — see test_bypassed_gate_raises). This pins the
        # general-kernel pairing the gate falls back to.
        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"s": [b"5"], b"ss": [b"\xc3\x9f"], b"a": [b"4"]}
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words([b"misses", b"sass"]))
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    def test_bypassed_gate_raises(self):
        # Passing scalar_units truthy for a plan the host gate rejects
        # must raise host-side, not silently corrupt the packed startp
        # encode (the wrapper re-validates when arrays are concrete).
        import jax.numpy as jnp

        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"s": [b"5"], b"ss": [b"\xc3\x9f"], b"a": [b"4"]}
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words([b"misses", b"sass"]))
        assert not scalar_units_for(plan)
        batch, _, _ = make_blocks(
            plan, start_word=0, start_rank=0, max_variants=8 * STRIDE,
            max_blocks=8, fixed_stride=STRIDE,
        )
        batch = pad_batch(batch, 8)
        with pytest.raises(ValueError, match="colliding match starts"):
            fused_expand_md5(
                jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
                jnp.asarray(plan.match_pos), jnp.asarray(plan.match_len),
                jnp.asarray(plan.match_radix),
                jnp.asarray(plan.match_val_start),
                jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
                jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
                jnp.asarray(batch.count),
                num_lanes=8 * STRIDE, out_width=plan.out_width,
                min_substitute=spec.effective_min,
                max_substitute=spec.max_substitute, block_stride=STRIDE,
                k_opts=1, scalar_units=True, interpret=True,
            )

    def test_bypassed_single_tier_raises(self):
        # A plan with active multi-byte spans qualifies as True but not
        # "single"; claiming "single" drops the coverage bitmask and must
        # be rejected the same way.
        import jax.numpy as jnp

        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        spec = AttackSpec(mode="default", algo="md5")
        sub = {b"ss": [b"\xc3\x9f"], b"a": [b"4"]}
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words([b"glass", b"haas"]))
        assert scalar_units_for(plan) is True
        batch, _, _ = make_blocks(
            plan, start_word=0, start_rank=0, max_variants=8 * STRIDE,
            max_blocks=8, fixed_stride=STRIDE,
        )
        batch = pad_batch(batch, 8)
        with pytest.raises(ValueError, match="multi-byte match spans"):
            fused_expand_md5(
                jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
                jnp.asarray(plan.match_pos), jnp.asarray(plan.match_len),
                jnp.asarray(plan.match_radix),
                jnp.asarray(plan.match_val_start),
                jnp.asarray(ct.val_bytes), jnp.asarray(ct.val_len),
                jnp.asarray(batch.word), jnp.asarray(batch.base_digits),
                jnp.asarray(batch.count),
                num_lanes=8 * STRIDE, out_width=plan.out_width,
                min_substitute=spec.effective_min,
                max_substitute=spec.max_substitute, block_stride=STRIDE,
                k_opts=1, scalar_units="single", interpret=True,
            )


class TestProductionWiring:
    """The full sweep runtime driving the REAL fused-kernel path: fake a
    TPU device so the gates open, force interpret-mode pallas (the
    ``A5GEN_PALLAS_INTERPRET`` hook), and run a production crack sweep on
    CPU. A threading bug in sweep -> make_crack_step ->
    fused_expand_md5(scalar_units=...) would otherwise only surface on
    real hardware."""

    def test_crack_sweep_through_scalar_kernel(self, monkeypatch):
        import hashlib

        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            iter_candidates,
        )
        from hashcat_a5_table_generator_tpu.runtime import (
            HitRecorder,
            Sweep,
            SweepConfig,
        )

        # Patch the gate itself, not jax.devices: the module-level jax
        # is shared, and the sharded path needs the REAL device list.
        monkeypatch.setattr(pe, "_on_tpu", lambda: True)
        monkeypatch.delenv("A5GEN_PALLAS", raising=False)
        monkeypatch.setenv("A5GEN_PALLAS_INTERPRET", "1")
        # Spy on the wrapper: if the gate silently fell back to the XLA
        # pair, this test would pass without testing anything.
        calls = []
        real = pe.fused_expand_md5

        def spy(*a, **kw):
            calls.append(kw.get("scalar_units"))
            return real(*a, **kw)

        monkeypatch.setattr(pe, "fused_expand_md5", spy)

        words = [b"glass", b"hello", b"oleander"]
        planted = [
            list(iter_candidates(words[0], K1_MAP, 0, 15))[1],
            list(iter_candidates(words[2], K1_MAP, 0, 15))[-1],
        ]
        digests = [hashlib.md5(c).digest() for c in planted]
        spec = AttackSpec(mode="default", algo="md5")
        sweep = Sweep(
            spec, K1_MAP, words, digests,
            # num_blocks=None: the production auto-geometry must itself
            # pick a kernel-eligible stride (PERF.md §11 -> 128).
            config=SweepConfig(lanes=1024, num_blocks=None),
        )
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        assert scalar_units_for(sweep.plan) == "single"
        rec = HitRecorder()
        res = sweep.run_crack(rec)
        assert calls and all(t == "single" for t in calls)
        assert res.n_hits == len(planted)
        assert sorted(h.candidate for h in res.hits) == sorted(planted)

    @pytest.mark.parametrize("mode,window", [
        ("suball", None),
        ("default", (1, 1)),  # windowed plan -> windowed scalar kernel
    ])
    def test_other_modes_through_kernel(self, monkeypatch, mode, window):
        import hashlib

        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            iter_candidates,
        )
        from hashcat_a5_table_generator_tpu.runtime import (
            HitRecorder,
            Sweep,
            SweepConfig,
        )

        # Patch the gate itself, not jax.devices: the module-level jax
        # is shared, and the sharded path needs the REAL device list.
        monkeypatch.setattr(pe, "_on_tpu", lambda: True)
        monkeypatch.delenv("A5GEN_PALLAS", raising=False)
        monkeypatch.setenv("A5GEN_PALLAS_INTERPRET", "1")
        calls = []
        wrapper = ("fused_expand_suball_md5" if mode == "suball"
                   else "fused_expand_md5")
        real = getattr(pe, wrapper)

        def spy(*a, **kw):
            calls.append(kw.get("scalar_units"))
            return real(*a, **kw)

        monkeypatch.setattr(pe, wrapper, spy)

        kw = dict(mode=mode, algo="md5")
        lo, hi = window or (0, 15)
        if window:
            kw.update(min_substitute=lo, max_substitute=hi)
        spec = AttackSpec(**kw)
        words = [b"glass", b"hello", b"oleander"]
        cands = [c for w in words for c in iter_candidates(
            w, K1_MAP, lo if window else 0, hi,
            substitute_all=(mode == "suball"))]
        planted = [cands[0], cands[-1]]
        digests = [hashlib.md5(c).digest() for c in planted]
        sweep = Sweep(spec, K1_MAP, words, digests,
                      config=SweepConfig(lanes=1024, num_blocks=None))
        if window:
            assert sweep.plan.windowed
        # The "single" tier is match-only; suball plans ride the plain
        # scalar tier (segments are disjoint, no start encode needed).
        want_tier = True if mode == "suball" else "single"
        rec = HitRecorder()
        res = sweep.run_crack(rec)
        assert calls and all(t == want_tier for t in calls)
        assert {h.candidate for h in res.hits} == set(planted)
        assert res.n_hits >= len(set(planted))

    def test_sharded_sweep_through_kernel(self, monkeypatch):
        # The shard_map'd crack step must thread the kernel flags too
        # (parallel.mesh -> make_fused_body); 2 virtual CPU devices,
        # interpret-mode pallas inside shard_map.
        import hashlib

        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 virtual devices")
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe
        from hashcat_a5_table_generator_tpu.oracle.engines import (
            iter_candidates,
        )
        from hashcat_a5_table_generator_tpu.runtime import (
            HitRecorder,
            Sweep,
            SweepConfig,
        )

        # Patch the gate itself, not jax.devices: the module-level jax
        # is shared, and the sharded path needs the REAL device list.
        monkeypatch.setattr(pe, "_on_tpu", lambda: True)
        monkeypatch.delenv("A5GEN_PALLAS", raising=False)
        monkeypatch.setenv("A5GEN_PALLAS_INTERPRET", "1")
        calls = []
        real = pe.fused_expand_md5

        def spy(*a, **kw):
            calls.append(kw.get("scalar_units"))
            return real(*a, **kw)

        monkeypatch.setattr(pe, "fused_expand_md5", spy)

        words = [b"glass", b"hello", b"oleander", b"misses"]
        planted = [list(iter_candidates(words[0], K1_MAP, 0, 15))[1]]
        spec = AttackSpec(mode="default", algo="md5")
        sweep = Sweep(
            spec, K1_MAP, words,
            [hashlib.md5(planted[0]).digest()],
            config=SweepConfig(lanes=1024, num_blocks=None, devices=2),
        )
        rec = HitRecorder()
        res = sweep.run_crack(rec)
        assert calls and all(t == "single" for t in calls)
        assert {h.candidate for h in res.hits} == set(planted)


#: 4-byte values reach multi-block output widths at small token counts,
#: keeping the interpret-mode cost of these tests bounded.
MB_MAP = {b"a": [b"\xf0\x9f\x98\x80"], b"s": [b"\xf0\x9f\x98\x81"]}


class TestMultiBlock:
    """Long candidates through chained hash blocks: each lane's digest
    must be the state after ITS OWN padding block, with short and long
    lanes mixed in one launch."""

    def _parity(self, spec, words, *, sub=MB_MAP, algo=None,
                num_blocks=8):
        algo = algo or spec.algo
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(words))
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            _hash_blocks_for,
        )

        scale = 2 if algo == "ntlm" else 1
        assert _hash_blocks_for(plan.out_width, scale) >= 2
        runner = (_run_both_suball if spec.mode.startswith("suball")
                  else _run_both)
        kw = {"num_blocks": num_blocks}
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        tier = scalar_units_for(plan)
        if tier:
            kw["scalar_units"] = tier
        saw = False
        for emit_x, emit_p, state_x, state_p in runner(
            spec, plan, ct, algo=algo, **kw
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    @pytest.mark.slow  # super-linear interpret cost: ~3k 2-block lanes
    def test_md5_mixed_block_counts(self):
        # Mixed 1/2-block lanes in one launch: the per-lane state select
        # must pick each lane's own padding block.
        self._parity(AttackSpec(mode="default", algo="md5"),
                     [b"go", b"assassin-sassafras-aa"])

    @pytest.mark.slow  # ~55 s interpret cost on the tier-1 host: the
    # per-lane padding-block select stays default-covered by
    # test_suball_two_blocks (G=4, 4 blocks); CI's slow steps run this
    def test_md5_mixed_block_counts_sampled(self, monkeypatch):
        # The default-run sample of the mixed-block contract: same
        # per-lane padding-block select, interpret-sized space (146
        # ranks — 'go' lanes stay 1-block, the long word's lanes 2-block;
        # G=2 keeps the padded interpret lanes at 256, not 1024).
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

        monkeypatch.setattr(pe, "_G", 2)
        self._parity(AttackSpec(mode="default", algo="md5"),
                     [b"go", b"assassin" + b"-" * 41], num_blocks=2)

    @pytest.mark.slow  # super-linear interpret cost: 3-block x windowed
    def test_md5_three_blocks_windowed(self):
        # 30 matchable positions x 4-byte values reach the 3-block width;
        # the count window keeps the enumerated space tiny (sum of
        # C(30, 0..2) = 466 ranks) AND covers windowed + multi-block
        # together.
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            _hash_blocks_for,
        )

        spec = AttackSpec(mode="default", algo="md5", min_substitute=0,
                          max_substitute=2)
        ct = compile_table(MB_MAP)
        plan = build_plan(spec, ct, pack_words([b"a" * 30 + b"x" * 10]))
        assert plan.windowed and _hash_blocks_for(plan.out_width, 1) == 3
        self._parity(spec, [b"a" * 30 + b"x" * 10])

    @pytest.mark.slow  # super-linear interpret cost: 80-round x ~3k lanes
    def test_sha1_two_blocks(self):
        self._parity(AttackSpec(mode="default", algo="sha1"),
                     [b"assassin-sassafras-aa"])

    @pytest.mark.slow  # 80-round interpret cost: ~31 s even sampled —
    # the per-lane padding-block select is algo-generic and stays
    # default-covered by the suball sample below; SHA-1
    # single-block parity stays fast (test_other_algos_match_xla).
    def test_sha1_two_blocks_sampled(self, monkeypatch):
        # Sample of the slow full run: SHA-1 through the 2-block tail
        # at 146 ranks.
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

        monkeypatch.setattr(pe, "_G", 2)
        self._parity(AttackSpec(mode="default", algo="sha1"),
                     [b"assassin" + b"-" * 41], num_blocks=2)

    @pytest.mark.slow  # super-linear interpret cost (see sha1 sample)
    def test_ntlm_two_blocks(self):
        self._parity(AttackSpec(mode="default", algo="ntlm"),
                     [b"go", b"assassin-sass-a"])

    def test_suball_two_blocks(self, monkeypatch):
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

        monkeypatch.setattr(pe, "_G", 4)
        self._parity(AttackSpec(mode="suball", algo="md5"),
                     [b"assassin-sassafras-aa"], num_blocks=4)

    @pytest.mark.slow  # ~80 s interpret cost on the tier-1 host — the
    # suite's single worst entry; the general kernel keeps fast
    # single-block parity (test_state_and_emit_match_xla) and the
    # multi-block tail stays default-covered by test_suball_two_blocks
    def test_general_kernel_two_blocks(self, monkeypatch):
        # K=2 table: the general (non-scalar) kernel through the shared
        # multi-block tail. The word's unmatched '-' tail pushes out_width
        # past one hash block (49 bytes + two 'a' matches growing 3 bytes
        # each = 56 > 55) while the variant space stays interpret-sized
        # (3^2 * 2^4 = 144 ranks).
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

        monkeypatch.setattr(pe, "_G", 2)
        sub = {b"a": [b"\xf0\x9f\x98\x80", b"\xf0\x9f\x98\x82"],
               b"s": [b"5"]}
        self._parity(AttackSpec(mode="default", algo="md5"),
                     [b"assassin" + b"-" * 41], sub=sub, num_blocks=2)


@pytest.mark.parametrize("algo", [
    # The SHA-1 arm's 80-round schedule costs ~27 s interpret-mode on
    # the tier-1 host; its BE schedule keeps fast default coverage via
    # the scalar/general sha1 emit-scheme arms, and the fused-kernel ×
    # non-md5 contract stays default-covered by the md4 arm.
    pytest.param("sha1", marks=pytest.mark.slow),
    # The NTLM arm's utf16-doubled widths cost ~17 s interpret-mode;
    # its MD4 compression stays default-covered by the md4 arm and the
    # utf16 fold by the suball NTLM parity + emit-scheme gw16 tests.
    pytest.param("ntlm", marks=pytest.mark.slow),
    "md4",
])
def test_other_algos_match_xla(algo):
    """SHA-1 (BE schedule + 5 state words), NTLM (UTF-16LE expansion +
    MD4), and raw MD4 through the fused kernel vs the XLA pair."""
    spec = AttackSpec(mode="default", algo=algo)
    ct, plan = _arrays(spec)
    saw = False
    for emit_x, emit_p, state_x, state_p in _run_both(
        spec, plan, ct, algo=algo
    ):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        saw = saw or emit_x.any()
    assert saw


def test_eligible_algo_bounds():
    base = dict(mode="default", algo="md5", windowed=False, block_stride=128,
                num_blocks=16, out_width=40, num_slots=8, token_width=16,
                max_val_len=2, max_options=2)
    for algo in ("md4", "sha1"):
        assert eligible(**{**base, "algo": algo})
    # NTLM halves the candidate budget (UTF-16LE doubling); with the
    # multi-block widening it is eligible up to out_width 91, mirroring
    # test_eligible_bounds.
    assert eligible(**{**base, "algo": "ntlm"})
    assert eligible(**{**base, "algo": "ntlm", "out_width": 27})
    assert eligible(**{**base, "algo": "ntlm", "out_width": 91})
    assert not eligible(**{**base, "algo": "ntlm", "out_width": 92})


@pytest.mark.parametrize("algo", ["sha1", "ntlm"])
def test_suball_other_algos_match_xla(algo):
    """The suball kernel's non-MD5 paths: SHA-1's 5-word state and NTLM's
    doubled-offset message through the segment formulation."""
    spec = AttackSpec(mode="suball", algo=algo)
    ct, plan = _arrays(spec, sub=SUBALL_TABLE)
    assert not plan.fallback.any()
    saw = False
    for emit_x, emit_p, state_x, state_p in _run_both_suball(
        spec, plan, ct, algo=algo
    ):
        np.testing.assert_array_equal(emit_x, emit_p)
        np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
        saw = saw or emit_x.any()
    assert saw


class TestWindowedKernel:
    """Count-windowed plans through the fused kernels: the in-kernel
    suffix-count DP walk must agree with the XLA windowed decode on emit
    mask and per-emitted-lane state, for match AND suball plans."""

    def _windowed_spec(self, mode, lo=1, hi=1):
        return AttackSpec(mode=mode, algo="md5", min_substitute=lo,
                          max_substitute=hi)

    @pytest.mark.parametrize("mode", ["default", "reverse"])
    def test_match_windowed_parity(self, mode):
        spec = self._windowed_spec(mode)
        ct, plan = _arrays(spec)
        assert plan.windowed and plan.win_v is not None
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    def test_match_windowed_wider_window(self):
        # K=2 options exercise the subtractive quotient chain (digits > 1).
        spec = self._windowed_spec("default", lo=2, hi=3)
        ct, plan = _arrays(spec)
        assert plan.windowed
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both(spec, plan, ct):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    def test_suball_windowed_parity(self):
        # Needs >= 2x lane saving to trigger windowed plans: words with
        # many unique keys and a tight window. K=2 on 's' exercises the
        # subtractive quotient chain; no value is itself a key (hazard-free
        # so no word routes to the oracle).
        sub = {b"a": [b"4"], b"e": [b"3"], b"l": [b"1"], b"o": [b"0"],
               b"s": [b"5", b"$"], b"u": [b"v"]}
        words = [b"aeolus", b"louse", b"sale", b"aeiou"]
        spec = self._windowed_spec("suball", lo=1, hi=1)
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(words))
        assert plan.windowed and not plan.fallback.any()
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both_suball(
            spec, plan, ct
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw

    def test_opts_for_config_accepts_windowed(self):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            opts_for_config,
        )

        spec = self._windowed_spec("default")
        ct, plan = _arrays(spec)
        assert plan.windowed
        assert opts_for_config(spec, plan, ct, block_stride=128,
                               num_blocks=16, require_tpu=False) == 2


@pytest.mark.slow  # ~17 s interpret cost: the G-never-changes-
# semantics contract is also exercised by every monkeypatched-_G
# sample above; CI's slow steps run the explicit A/B
def test_grid_height_override_parity(monkeypatch):
    """_G (blocks per grid step) is probe-tunable (A5GEN_PALLAS_G):
    G=16 must produce the identical emit/state stream as the default
    G=8 — geometry must never change semantics."""
    import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe

    spec = AttackSpec(mode="default", algo="md5")
    ct, plan = _arrays(spec)
    monkeypatch.setattr(pe, "_G", 8)  # pin: env may have set another G
    base = _run_both(spec, plan, ct, num_blocks=16)
    monkeypatch.setattr(pe, "_G", 16)
    wide = _run_both(spec, plan, ct, num_blocks=16)
    saw = False
    for (ex, ep, sx, sp), (ex2, ep2, sx2, sp2) in zip(base, wide):
        np.testing.assert_array_equal(ep, ep2)
        np.testing.assert_array_equal(sp[ep], sp2[ep2])
        saw = saw or ep.any()
    assert saw  # the comparison must not be vacuous


class TestCascadeClosure:
    """Cascade-CLOSED suball plans through the fused kernel: the joint
    value select (digits of the slot AND its hazard successors) must match
    the XLA closure path bit-for-bit, and the gates must route closed
    plans to the general kernel at the widened K."""

    def _parity(self, sub, words, spec=None, expect_windowed=None):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            scalar_units_for,
        )

        spec = spec or AttackSpec(mode="suball", algo="md5")
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(words))
        assert plan.closed is not None and plan.closed.any()
        assert scalar_units_for(plan) is False  # joint values: general only
        if expect_windowed is not None:
            assert plan.windowed == expect_windowed
        saw = False
        for emit_x, emit_p, state_x, state_p in _run_both_suball(
            spec, plan, ct
        ):
            np.testing.assert_array_equal(emit_x, emit_p)
            np.testing.assert_array_equal(state_x[emit_x], state_p[emit_p])
            saw = saw or emit_x.any()
        assert saw
        return plan

    def test_simple_chain(self):
        self._parity({b"a": [b"b"], b"b": [b"c"]},
                     [b"ab", b"a", b"aabb", b"zz"])

    def test_multi_option_joint_tables(self):
        # 2-option slot with a 2-option successor: joint tables reach 6
        # rows; mixed closed/clean/fallback words in one launch.
        self._parity({b"a": [b"b", b"bb"], b"b": [b"c", b"d"]},
                     [b"ab", b"ba", b"b", b"xx", b"aab"])

    def test_azerty_hazard_words(self):
        from hashcat_a5_table_generator_tpu.tables.layouts import (
            BUILTIN_LAYOUTS,
        )

        sub = BUILTIN_LAYOUTS["qwerty-azerty"].to_substitution_map()
        plan = self._parity(sub, [b"aqua", b"zw", b"ma,am", b"pass"])
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            k_vals_for,
            opts_for_config,
        )

        # The production gate must admit the closed plan at the widened K.
        spec = AttackSpec(mode="suball", algo="md5")
        assert opts_for_config(
            spec, plan, compile_table(sub), block_stride=STRIDE,
            num_blocks=8, require_tpu=False,
        ) == k_vals_for(plan) == plan.close_opts

    def test_windowed_closed_plan(self):
        # Count-windowed decode + joint closure values in one kernel.
        spec = AttackSpec(mode="suball", algo="md5", min_substitute=1,
                          max_substitute=1)
        self._parity({b"a": [b"b"], b"b": [b"c"], b"x": [b"y"],
                      b"z": [b"q"]},
                     [b"abxz", b"axzb", b"xz"], spec=spec,
                     expect_windowed=True)

    def test_scalar_units_path_rejects_closed(self):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            fused_expand_suball_md5,
        )

        ct = compile_table({b"a": [b"b"], b"b": [b"c"]})
        plan = build_plan(AttackSpec(mode="suball", algo="md5"), ct,
                          pack_words([b"ab"]))
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="scalar-units"):
            fused_expand_suball_md5(
                jnp.asarray(plan.tokens), jnp.asarray(plan.lengths),
                jnp.asarray(plan.pat_radix),
                jnp.asarray(plan.pat_val_start),
                jnp.asarray(plan.seg_orig_start),
                jnp.asarray(plan.seg_orig_len), jnp.asarray(plan.seg_pat),
                jnp.asarray(plan.cval_bytes), jnp.asarray(plan.cval_len),
                jnp.zeros(8, jnp.int32),
                jnp.zeros((8, plan.num_slots), jnp.int32),
                jnp.zeros(8, jnp.int32),
                num_lanes=8 * STRIDE, out_width=plan.out_width,
                min_substitute=0, max_substitute=15, block_stride=STRIDE,
                k_opts=2, scalar_units=True, interpret=True,
                close_next=jnp.asarray(plan.close_next),
                close_mul=jnp.asarray(plan.close_mul),
            )

"""Sweep runtime: checkpoint/resume, sinks, progress, candidate parity with
the oracle (incl. oracle-fallback interleaving), crack-mode hit pipeline."""

import hashlib
import io
import json

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.runtime import (
    CandidateWriter,
    CheckpointState,
    HitRecorder,
    ProgressReporter,
    Sweep,
    SweepConfig,
    SweepCursor,
    load_checkpoint,
    save_checkpoint,
    sweep_fingerprint,
)
from hashcat_a5_table_generator_tpu.utils.md4 import md4, ntlm

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a"]
SMALL_CFG = dict(lanes=256, num_blocks=16)


def oracle_lines(spec, sub_map, words):
    out = []
    for w in words:
        out.extend(
            iter_candidates(
                w,
                sub_map,
                spec.min_substitute,
                spec.max_substitute,
                substitute_all=spec.mode.startswith("suball"),
                reverse=spec.mode in ("reverse", "suball-reverse"),
            )
        )
    return out


class TestMD4:
    def test_rfc1320_vectors(self):
        vectors = {
            b"": "31d6cfe0d16ae931b73c59d7e0c089c0",
            b"a": "bde52cb31de33e46245e05fbdbd6fb24",
            b"abc": "a448017aaf21d8525fc10ae87aa6729d",
            b"message digest": "d9130a8164549fe818874806e1c7014b",
            b"abcdefghijklmnopqrstuvwxyz": "d79e1c308aa5bbcdeea8ed63df412da9",
            b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789":
                "043f8582f241db351ce627e153e7f0e4",
            b"1234567890" * 8: "e33b4ddc9c38f2199c3e7b164fcc0536",
        }
        for msg, want in vectors.items():
            assert md4(msg).hex() == want

    def test_ntlm_known(self):
        # Well-known NTLM("password") vector.
        assert ntlm(b"password").hex() == "8846f7eaee8fb117ad06bdd830b7586c"


class TestCheckpoint:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "ck.json")
        st = CheckpointState(
            fingerprint="f" * 64,
            cursor=SweepCursor(word=7, rank=123456789012345678901234567890),
            n_emitted=42,
            n_hits=2,
            hits=[(1, 5), (3, 10**25)],
            fallback_done=1,
            wall_s=1.5,
        )
        save_checkpoint(path, st)
        got = load_checkpoint(path, "f" * 64)
        assert got == st  # bigint rank/hits survive JSON via str round-trip

    def test_missing_returns_none(self, tmp_path):
        assert load_checkpoint(str(tmp_path / "nope.json"), "x") is None

    def test_fingerprint_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "ck.json")
        save_checkpoint(path, CheckpointState(fingerprint="aaa"))
        with pytest.raises(ValueError, match="different sweep"):
            load_checkpoint(path, "bbb")

    def test_fingerprint_sensitivity(self):
        base = sweep_fingerprint("default", "md5", 0, 15, LEET, WORDS, [])
        assert base != sweep_fingerprint("reverse", "md5", 0, 15, LEET, WORDS, [])
        assert base != sweep_fingerprint("default", "md5", 1, 15, LEET, WORDS, [])
        # Value-list ORDER is semantic (Q2 first-option).
        flipped = dict(LEET, s=[b"5", b"$"])
        flipped = {b"a": LEET[b"a"], b"o": LEET[b"o"], b"s": [b"5", b"$"],
                   b"e": LEET[b"e"]}
        assert base != sweep_fingerprint("default", "md5", 0, 15, flipped, WORDS, [])
        # Key insertion order is NOT (tables merge into one map).
        reordered = dict(reversed(list(LEET.items())))
        assert base == sweep_fingerprint("default", "md5", 0, 15, reordered, WORDS, [])

    def test_fingerprint_packed_equals_word_list(self):
        # The buffer-level PackedWords path must produce the SAME
        # fingerprint as the per-word list path, at ANY packing width.
        from hashcat_a5_table_generator_tpu.ops.packing import pack_words

        base = sweep_fingerprint("default", "md5", 0, 15, LEET, WORDS, [])
        for width in (None, 64, 128):
            packed = pack_words(WORDS, width=width)
            assert sweep_fingerprint(
                "default", "md5", 0, 15, LEET, packed, []
            ) == base


class TestSinks:
    def test_candidate_writer_lines(self):
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            w.emit(b"abc")
            w.emit(b"x=y")
        assert buf.getvalue() == b"abc\nx=y\n"
        assert w.n_written == 2

    def test_hex_unsafe_wrapping(self):
        buf = io.BytesIO()
        with CandidateWriter(buf, hex_unsafe=True) as w:
            w.emit(b"ok")
            w.emit(b"bad\nline")
            w.emit(b"$HEX[00]")
        lines = buf.getvalue().split(b"\n")
        assert lines[0] == b"ok"
        assert lines[1] == b"$HEX[6261640a6c696e65]"
        assert lines[2] == b"$HEX[244845585b30305d]"


class TestProgress:
    def test_rate_limit_and_final(self):
        out = io.StringIO()
        t = [0.0]
        rep = ProgressReporter(
            10, every_s=5.0, stream=out, clock=lambda: t[0]
        )
        rep.update(words_done=1, emitted=10, hits=0)  # t=0 emits
        t[0] = 1.0
        rep.update(words_done=2, emitted=20, hits=0)  # suppressed
        t[0] = 6.0
        rep.update(words_done=3, emitted=40, hits=1)  # emits
        rep.final(words_done=10, emitted=100, hits=1)  # forced
        lines = [json.loads(x) for x in out.getvalue().splitlines()]
        assert len(lines) == 3
        assert lines[1]["progress"]["words"] == [3, 10]
        assert lines[1]["progress"]["cand_per_sec"] == pytest.approx(5.0)
        assert lines[2]["progress"]["words"] == [10, 10]


@pytest.mark.parametrize("mode", ["default", "reverse", "suball", "suball-reverse"])
def test_candidates_mode_matches_oracle(mode):
    spec = AttackSpec(mode=mode, algo="md5")
    sweep = Sweep(spec, LEET, WORDS, config=SweepConfig(**SMALL_CFG))
    buf = io.BytesIO()
    with CandidateWriter(buf) as w:
        res = sweep.run_candidates(w)
    got = buf.getvalue().splitlines()
    want = oracle_lines(spec, LEET, WORDS)
    # Global word order; per-word multiset parity (Q9).
    from collections import Counter

    assert Counter(got) == Counter(want)
    assert res.n_emitted == len(want) == w.n_written


def test_candidates_mode_fallback_interleaved_in_word_order():
    # "acb" + {a=c, cb=Z} in suball mode is a boundary-CROSSING cascade
    # hazard (the inserted 'c' extends the original 'b' into a new 'cb'
    # match) — genuinely pathological, so it stays oracle-routed even with
    # cascade closure; surrounding words run on device. Word-order must
    # hold globally.
    sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
    words = [b"zz", b"acb", b"za"]
    spec = AttackSpec(mode="suball", algo="md5")
    sweep = Sweep(spec, sub, words, config=SweepConfig(**SMALL_CFG))
    assert len(sweep.fallback_rows) >= 1, "fixture must exercise fallback"
    buf = io.BytesIO()
    with CandidateWriter(buf) as w:
        sweep.run_candidates(w)
    got = buf.getvalue().splitlines()
    # Reconstruct expected per-word segments in word order.
    segments = [oracle_lines(spec, sub, [x]) for x in words]
    from collections import Counter

    pos = 0
    for seg in segments:
        chunk = got[pos : pos + len(seg)]
        assert Counter(chunk) == Counter(seg)
        pos += len(seg)
    assert pos == len(got)


@pytest.mark.parametrize("algo,href", [
    ("md5", lambda b: hashlib.md5(b).digest()),
    ("sha1", lambda b: hashlib.sha1(b).digest()),
    ("ntlm", ntlm),
])
def test_crack_mode_hits_and_reverification(algo, href):
    spec = AttackSpec(mode="default", algo=algo)
    oracle = oracle_lines(spec, LEET, [b"password"])
    planted = sorted({oracle[0], oracle[-1], oracle[len(oracle) // 2]})
    digests = [href(c) for c in planted]
    digests += [href(b"decoy%d" % i) for i in range(50)]
    sweep = Sweep(spec, LEET, WORDS, digests, config=SweepConfig(**SMALL_CFG))
    res = sweep.run_crack()
    assert sorted({h.candidate for h in res.hits}) == planted
    for h in res.hits:
        assert href(h.candidate).hex() == h.digest_hex
    assert res.n_emitted == len(oracle_lines(spec, LEET, WORDS))


def test_fallback_prefetcher_overlaps_and_cleans_up():
    """The oracle-fallback path runs on a producer thread (VERDICT r3 #5):
    the prefetcher must engage whenever fallback rows exist, deliver
    byte-identical candidates in word order, and leave no live thread after
    the sweep."""
    import threading

    from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
        CheckpointState,
    )
    from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig

    # Boundary-crossing ReplaceAll hazard: the value 'c' inserted for 'a'
    # can join the neighboring original 'b' into a new 'cb' match — not
    # closable, so these words are genuinely oracle-routed.
    sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
    words = [b"acb", b"cba", b"zz", b"aacb"]
    spec = AttackSpec(mode="suball", algo="md5")
    sweep = Sweep(spec, sub, words, config=SweepConfig(lanes=64, num_blocks=16))
    assert sweep.fallback_rows  # hazard words exist
    pre = sweep._make_prefetcher(CheckpointState(fingerprint="x"))
    assert pre is not None  # prefetcher engages whenever fallback rows exist
    pre.close()
    assert not pre._thread.is_alive()

    import io

    from hashcat_a5_table_generator_tpu.runtime.sinks import CandidateWriter

    buf = io.BytesIO()
    with CandidateWriter(stream=buf) as writer:
        sweep.run_candidates(writer, resume=False)
    # Producer threads are torn down (close() drains + joins); check the
    # named thread specifically — JAX/XLA may lazily spawn unrelated
    # helper threads during the first compile.
    assert not any(
        t.name == "a5-fallback-oracle" and t.is_alive()
        for t in threading.enumerate()
    )
    from collections import Counter

    want = Counter()
    for w in words:
        want.update(iter_candidates(w, sub, 0, 15, substitute_all=True))
    assert Counter(buf.getvalue().splitlines()) == want


def test_crack_mode_fallback_hits():
    # Boundary-crossing hazard: 'acb' stays oracle-routed (not closable).
    sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
    words = [b"zz", b"acb", b"za"]
    spec = AttackSpec(mode="suball", algo="md5")
    fb_cand = oracle_lines(spec, sub, [b"acb"])[-1]
    dev_cand = oracle_lines(spec, sub, [b"zz"])[-1]
    digests = [hashlib.md5(fb_cand).digest(), hashlib.md5(dev_cand).digest()]
    sweep = Sweep(spec, sub, words, digests, config=SweepConfig(**SMALL_CFG))
    res = sweep.run_crack()
    assert {h.candidate for h in res.hits} == {fb_cand, dev_cand}


def test_crack_checkpoint_resume_equivalence(tmp_path):
    spec = AttackSpec(mode="default", algo="md5")
    oracle = oracle_lines(spec, LEET, WORDS)
    planted = sorted({oracle[3], oracle[-2]})
    digests = [hashlib.md5(c).digest() for c in planted]

    # Uninterrupted run.
    full = Sweep(spec, LEET, WORDS, digests, config=SweepConfig(**SMALL_CFG))
    want = full.run_crack()

    # Interrupted run: small lanes force several launches (checkpoint after
    # each — every_s=0); the second planted hit lands in a later launch, so
    # raising on it leaves a mid-sweep checkpoint behind.  This pins the
    # PER-LAUNCH chunked cadence, so the superstep executor (whose
    # checkpoints land at superstep boundaries — several launches each,
    # more than this tiny sweep has) is pinned off; its own resume
    # equivalence lives in tests/test_superstep.py.
    path = str(tmp_path / "sweep.json")
    cfg = SweepConfig(lanes=64, num_blocks=16, superstep=0,
                      checkpoint_path=path, checkpoint_every_s=0.0)

    class Boom(Exception):
        pass

    class ExplodingRecorder(HitRecorder):
        def emit(self, record):
            super().emit(record)
            if len(self.hits) == 2:
                raise Boom()

    first = Sweep(spec, LEET, WORDS, digests, config=cfg)
    with pytest.raises(Boom):
        first.run_crack(ExplodingRecorder())
    # The checkpoint from the partial run exists, matches the sweep, and
    # sits mid-sweep (so the resume below does real work).
    partial = load_checkpoint(path, first.fingerprint)
    assert partial is not None
    assert partial.cursor.word < len(WORDS)
    assert len(partial.hits) == 1

    second = Sweep(spec, LEET, WORDS, digests, config=cfg)
    got = second.run_crack()
    assert got.resumed
    assert sorted(h.candidate for h in got.hits) == sorted(
        h.candidate for h in want.hits
    )
    assert {h.candidate for h in got.hits} == set(planted)


def test_candidates_checkpoint_resume_completes(tmp_path):
    spec = AttackSpec(mode="default", algo="md5")
    path = str(tmp_path / "cand.json")
    cfg = SweepConfig(checkpoint_path=path, checkpoint_every_s=0.0, **SMALL_CFG)

    sweep = Sweep(spec, LEET, WORDS, config=cfg)
    buf = io.BytesIO()
    with CandidateWriter(buf) as w:
        sweep.run_candidates(w)
    ck = load_checkpoint(path, sweep.fingerprint)
    assert ck.cursor.word == len(WORDS)

    # Resuming a COMPLETE sweep emits nothing further.
    buf2 = io.BytesIO()
    again = Sweep(spec, LEET, WORDS, config=cfg)
    with CandidateWriter(buf2) as w2:
        res = again.run_candidates(w2)
    assert res.resumed
    assert buf2.getvalue() == b""


def test_checkpoint_ignores_launch_geometry(tmp_path):
    # Cursor is (word, rank): resuming with different lanes/blocks is legal
    # and produces the remaining multiset exactly.
    spec = AttackSpec(mode="default", algo="md5")
    path = str(tmp_path / "geo.json")

    cfg1 = SweepConfig(lanes=64, num_blocks=4, checkpoint_path=path,
                       checkpoint_every_s=1e9)  # only the forced final save
    s1 = Sweep(spec, LEET, WORDS, config=cfg1)
    # Manually save a mid-sweep checkpoint at an arbitrary cursor.
    state = CheckpointState(
        fingerprint=s1.fingerprint, cursor=SweepCursor(word=1, rank=3),
        n_emitted=0,
    )
    save_checkpoint(path, state)

    cfg2 = SweepConfig(lanes=512, num_blocks=32, checkpoint_path=path,
                       checkpoint_every_s=1e9)
    s2 = Sweep(spec, LEET, WORDS, config=cfg2)
    buf = io.BytesIO()
    with CandidateWriter(buf) as w:
        s2.run_candidates(w)
    got = buf.getvalue().splitlines()

    # Expected: word 1's variants from rank 3 on, then words 2..end.
    # Ranks 0-2 are skipped; rank 0 is the never-emitted original (Q1), and
    # ranks 1-2 decode to specific candidates we can subtract exactly.
    from collections import Counter

    from hashcat_a5_table_generator_tpu.models.attack import decode_variant

    w1 = oracle_lines(spec, LEET, [WORDS[1]])
    rest = oracle_lines(spec, LEET, WORDS[2:])
    skipped = [decode_variant(s2.plan, s2.ct, spec, 1, r) for r in (1, 2)]
    want = Counter(w1) - Counter(skipped) + Counter(rest)
    assert Counter(got) == want


class TestMultiDeviceSweep:
    """The sharded sweep through the PUBLIC Sweep path (not a hand-rolled
    shard_map loop): SweepConfig(devices=N) must produce exactly the
    single-device results on the 8-virtual-CPU-device mesh."""

    # Auto resolves to stride for these divisible geometries (the
    # backend-independent rule, PERF.md §4c); layout=True keeps the packed
    # layout covered under sharding.
    @pytest.mark.parametrize("layout", [None, True], ids=["auto", "packed"])
    @pytest.mark.parametrize("mode", ["default", "suball"])
    def test_candidates_equal_single_device(self, mode, layout):
        spec = AttackSpec(mode=mode, algo="md5")

        def run(devices):
            cfg = SweepConfig(lanes=64, num_blocks=16, devices=devices,
                              packed_blocks=layout)
            sweep = Sweep(spec, LEET, WORDS, config=cfg)
            buf = io.BytesIO()
            with CandidateWriter(buf) as w:
                res = sweep.run_candidates(w)
            return res.n_emitted, buf.getvalue()

        n1, out1 = run(1)
        n8, out8 = run(8)
        # Byte-identical streams: device lane slices are cursor-ordered, so
        # sharding must not even reorder candidates.
        assert out8 == out1
        assert n8 == n1 == len(oracle_lines(spec, LEET, WORDS))

    @pytest.mark.parametrize("layout", [None, True], ids=["auto", "packed"])
    def test_crack_hits_equal_single_device(self, layout):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, LEET, WORDS)
        planted = sorted({oracle[0], oracle[len(oracle) // 3], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(40)]

        def run(devices):
            cfg = SweepConfig(lanes=64, num_blocks=16, devices=devices,
                              packed_blocks=layout)
            sweep = Sweep(spec, LEET, WORDS, digests, config=cfg)
            res = sweep.run_crack()
            return res.n_emitted, [
                (h.word_index, h.variant_rank, h.candidate) for h in res.hits
            ]

        n1, hits1 = run(1)
        n8, hits8 = run(8)
        assert hits8 == hits1
        assert {h[2] for h in hits8} == set(planted)
        assert n8 == n1 == len(oracle)

    def test_crack_with_fallback_words_equal_single_device(self):
        # Genuinely pathological (boundary-crossing) hazard words route
        # through the oracle on BOTH paths and must interleave identically
        # with the sharded device stream.
        sub = {b"a": [b"c"], b"cb": [b"Z"], b"z": [b"q"]}
        words = [b"zz", b"acb", b"za", b"zacb", b"azz"]
        spec = AttackSpec(mode="suball", algo="md5")
        fb_cand = oracle_lines(spec, sub, [b"acb"])[-1]
        dev_cand = oracle_lines(spec, sub, [b"azz"])[-1]
        digests = [hashlib.md5(fb_cand).digest(),
                   hashlib.md5(dev_cand).digest()]

        def run(devices):
            cfg = SweepConfig(lanes=64, num_blocks=16, devices=devices)
            sweep = Sweep(spec, sub, words, digests, config=cfg)
            assert len(sweep.fallback_rows) >= 1
            res = sweep.run_crack()
            return [(h.word_index, h.candidate) for h in res.hits]

        assert run(8) == run(1)

    def test_checkpoint_crosses_device_counts(self, tmp_path):
        # A mid-sweep checkpoint taken at one device count resumes at
        # another: the cursor is geometry- and mesh-independent.
        spec = AttackSpec(mode="default", algo="md5")
        path = str(tmp_path / "mesh.json")

        cfg1 = SweepConfig(lanes=64, num_blocks=4, checkpoint_path=path,
                           checkpoint_every_s=1e9)
        s1 = Sweep(spec, LEET, WORDS, config=cfg1)
        save_checkpoint(path, CheckpointState(
            fingerprint=s1.fingerprint, cursor=SweepCursor(word=1, rank=3),
        ))

        def finish(devices):
            save_checkpoint(path, CheckpointState(
                fingerprint=s1.fingerprint,
                cursor=SweepCursor(word=1, rank=3),
            ))
            cfg = SweepConfig(lanes=128, num_blocks=16, devices=devices,
                              checkpoint_path=path, checkpoint_every_s=1e9)
            s = Sweep(spec, LEET, WORDS, config=cfg)
            buf = io.BytesIO()
            with CandidateWriter(buf) as w:
                s.run_candidates(w)
            return buf.getvalue()

        assert finish(8) == finish(1)

    def test_devices_auto_resolves_all_local(self):
        import jax

        spec = AttackSpec(mode="default", algo="md5")
        cfg = SweepConfig(lanes=64, num_blocks=16, devices=None)
        sweep = Sweep(spec, LEET, WORDS, config=cfg)
        assert sweep._resolve_devices() == len(jax.devices()) == 8

    def test_too_many_devices_raises(self):
        spec = AttackSpec(mode="default", algo="md5")
        cfg = SweepConfig(lanes=64, num_blocks=16, devices=64)
        sweep = Sweep(spec, LEET, WORDS, config=cfg)
        with pytest.raises(ValueError, match="devices"):
            sweep.run_candidates(CandidateWriter(io.BytesIO()))


def test_potfile_line_wraps_colon_plains():
    from hashcat_a5_table_generator_tpu.runtime.sinks import potfile_line

    assert potfile_line("ab" * 16, b"pa:ss") == (
        b"ab" * 16 + b":$HEX[" + b"pa:ss".hex().encode() + b"]\n"
    )
    assert potfile_line("ab" * 16, b"plain") == b"ab" * 16 + b":plain\n"
    assert potfile_line("ab" * 16, b"nl\nin") == (
        b"ab" * 16 + b":$HEX[" + b"nl\nin".hex().encode() + b"]\n"
    )


def test_progress_seed_emitted_resumed_rate():
    # A resumed sweep's first progress line must not attribute prior-run
    # output to this process's first window (ADVICE r1).
    t = [0.0]

    out = io.StringIO()
    rep = ProgressReporter(10, every_s=1.0, stream=out, clock=lambda: t[0])
    rep.seed_emitted(1_000_000)  # checkpointed n_emitted from a prior run
    t[0] = 2.0
    rep.update(words_done=5, emitted=1_000_100, hits=0)
    line = json.loads(out.getvalue().splitlines()[-1])
    assert line["progress"]["cand_per_sec"] == pytest.approx(50.0)


class TestAutoNumBlocks:
    """num_blocks=None resolves once the run kind is known (PERF.md §9b):
    the fused-kernel strides only apply to crack launches on TPU; on the
    CPU backend (this suite) every kind resolves to the XLA-best
    lanes/128."""

    def test_auto_resolves_on_candidates_run(self):
        spec = AttackSpec(mode="default", algo="md5")
        sweep = Sweep(spec, LEET, WORDS,
                      config=SweepConfig(lanes=256, num_blocks=None))
        assert sweep.config.num_blocks is None  # deferred until the run
        buf = io.BytesIO()
        with CandidateWriter(buf) as w:
            sweep.run_candidates(w)
        assert sweep.config.num_blocks == 2  # 256 // 128
        expected = oracle_lines(spec, LEET, WORDS)
        assert sorted(buf.getvalue().splitlines()) == sorted(expected)

    def test_auto_resolves_on_crack_run(self):
        spec = AttackSpec(mode="default", algo="md5")
        target = next(iter_candidates(b"password", LEET, 1, 15))
        digests = [hashlib.md5(target).digest()]
        sweep = Sweep(spec, LEET, WORDS, digests,
                      config=SweepConfig(lanes=256, num_blocks=None))
        res = sweep.run_crack()
        assert sweep.config.num_blocks == 2
        assert any(h.candidate == target for h in res.hits)

    def test_resolve_block_stride_rejects_unresolved_auto(self):
        with pytest.raises(ValueError, match="auto"):
            SweepConfig(lanes=256, num_blocks=None).resolve_block_stride()


class TestEnvAccessors:
    """Every ``runtime/env.py`` accessor: the documented off spelling
    takes effect, and a typo spelling warns ONCE per process (the
    ``env_warn_once`` convention) while keeping the default — a typo
    must never silently change behavior OR spam per-word loops."""

    @pytest.fixture(autouse=True)
    def _fresh_warn_state(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime import env as env_mod

        monkeypatch.setattr(env_mod, "_WARNED", set())

    def test_read_env_rejects_foreign_names(self):
        from hashcat_a5_table_generator_tpu.runtime.env import read_env

        with pytest.raises(ValueError, match="A5GEN"):
            read_env("PATH")

    def test_read_env_grandfathers_a5_native(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime.env import read_env

        monkeypatch.setenv("A5_NATIVE", "1")
        assert read_env("A5_NATIVE") == "1"

    def test_env_warn_once_dedupes_by_name_and_value(self, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import env_warn_once

        env_warn_once("A5GEN_X", "a", "first spelling")
        env_warn_once("A5GEN_X", "a", "first spelling")
        env_warn_once("A5GEN_X", "b", "second spelling")
        err = capsys.readouterr().err
        assert err.count("first spelling") == 1
        assert err.count("second spelling") == 1

    GATES = [
        ("pipeline_enabled", "A5GEN_PIPELINE"),
        ("stream_enabled", "A5GEN_STREAM"),
        ("telemetry_enabled", "A5GEN_TELEMETRY"),
        ("pack_enabled", "A5GEN_PACK"),
        ("pair_enabled", "A5GEN_PAIR"),
    ]

    @pytest.mark.parametrize("accessor,var", GATES)
    def test_opt_out_gate_off_spellings(self, accessor, var, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime import env as env_mod

        fn = getattr(env_mod, accessor)
        monkeypatch.delenv(var, raising=False)
        assert fn() is True
        for spelling in ("off", "0", "no", "OFF"):
            monkeypatch.setenv(var, spelling)
            assert fn() is False

    @pytest.mark.parametrize("accessor,var", GATES)
    def test_opt_out_gate_typo_warns_once_keeps_default(
        self, accessor, var, monkeypatch, capsys
    ):
        from hashcat_a5_table_generator_tpu.runtime import env as env_mod

        fn = getattr(env_mod, accessor)
        monkeypatch.setenv(var, "offf")
        assert fn() is True
        assert fn() is True
        err = capsys.readouterr().err
        assert err.count(f"unrecognized {var}='offf'") == 1

    def test_refuse_threshold_arms(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            refuse_threshold,
        )

        monkeypatch.delenv("A5GEN_REFUSE", raising=False)
        assert refuse_threshold() == 0.5
        monkeypatch.setenv("A5GEN_REFUSE", "off")
        assert refuse_threshold() is None
        monkeypatch.setenv("A5GEN_REFUSE", "0.25")
        assert refuse_threshold() == 0.25
        monkeypatch.setenv("A5GEN_REFUSE", "1.5")  # out of (0, 1]
        assert refuse_threshold() == 0.5
        assert refuse_threshold() == 0.5
        err = capsys.readouterr().err
        assert err.count("unrecognized A5GEN_REFUSE='1.5'") == 1

    def test_tune_profile_setting_arms(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            tune_profile_setting,
        )

        monkeypatch.delenv("A5GEN_TUNE_PROFILE", raising=False)
        assert tune_profile_setting() == ""
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", "off")
        assert tune_profile_setting() is None
        monkeypatch.setenv("A5GEN_TUNE_PROFILE", "/tmp/profiles")
        assert tune_profile_setting() == "/tmp/profiles"

    def test_schema_cache_dir_arms(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            schema_cache_dir,
        )

        monkeypatch.delenv("A5GEN_SCHEMA_CACHE", raising=False)
        assert schema_cache_dir() is None
        monkeypatch.setenv("A5GEN_SCHEMA_CACHE", "")
        assert schema_cache_dir() is None
        monkeypatch.setenv("A5GEN_SCHEMA_CACHE", "/tmp/sc")
        assert schema_cache_dir() == "/tmp/sc"

    def test_schema_cache_max_mb_arms(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            schema_cache_max_mb,
        )

        monkeypatch.delenv("A5GEN_SCHEMA_CACHE_MAX_MB", raising=False)
        assert schema_cache_max_mb() is None
        monkeypatch.setenv("A5GEN_SCHEMA_CACHE_MAX_MB", "64")
        assert schema_cache_max_mb() == 64.0
        monkeypatch.setenv("A5GEN_SCHEMA_CACHE_MAX_MB", "-3")
        assert schema_cache_max_mb() is None
        assert schema_cache_max_mb() is None
        err = capsys.readouterr().err
        assert err.count("unrecognized A5GEN_SCHEMA_CACHE_MAX_MB='-3'") == 1

    def test_faults_spec_arms(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime.env import faults_spec

        monkeypatch.delenv("A5GEN_FAULTS", raising=False)
        assert faults_spec() is None
        monkeypatch.setenv("A5GEN_FAULTS", "")
        assert faults_spec() is None
        monkeypatch.setenv("A5GEN_FAULTS", "superstep.dispatch:nth=2")
        assert faults_spec() == "superstep.dispatch:nth=2"

    def test_emit_scheme_arms_and_warns_once(self, monkeypatch, capsys):
        # Regression: emit_scheme used to print its typo warning on
        # EVERY call — and it is called per plan build.
        from hashcat_a5_table_generator_tpu.runtime.env import emit_scheme

        monkeypatch.delenv("A5GEN_EMIT", raising=False)
        assert emit_scheme() == "perslot"
        monkeypatch.setenv("A5GEN_EMIT", "bytescan")
        assert emit_scheme() == "bytescan"
        monkeypatch.setenv("A5GEN_EMIT", "byteskan")
        assert emit_scheme() == "perslot"
        assert emit_scheme() == "perslot"
        err = capsys.readouterr().err
        assert err.count("unrecognized A5GEN_EMIT='byteskan'") == 1

    def test_pallas_gate_typo_warns_once(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            enabled_by_env,
        )

        monkeypatch.setenv("A5GEN_PALLAS", "offf")
        assert enabled_by_env() is True
        assert enabled_by_env() is True
        err = capsys.readouterr().err
        assert err.count("unrecognized A5GEN_PALLAS='offf'") == 1

    def test_pallas_grid_height_typo_warns_once(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
            _grid_height_from_env,
        )

        monkeypatch.setenv("A5GEN_PALLAS_G", "eight")
        assert _grid_height_from_env() == 8
        assert _grid_height_from_env() == 8
        err = capsys.readouterr().err
        assert err.count("invalid A5GEN_PALLAS_G='eight'") == 1

    def test_dcn_timeout_typo_warns_once(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.parallel import multihost

        monkeypatch.setenv("A5GEN_DCN_TIMEOUT", "soon")
        assert multihost._dcn_timeout() == multihost._DEFAULT_DCN_TIMEOUT
        assert multihost._dcn_timeout() == multihost._DEFAULT_DCN_TIMEOUT
        err = capsys.readouterr().err
        assert err.count("invalid A5GEN_DCN_TIMEOUT='soon'") == 1

"""CPU-oracle engine tests: the verified behavioral contract of SURVEY.md §2.4.

Every Q-vector listed there (empirically confirmed against a faithful
transcription of the reference) is asserted here; these are the anchors the
TPU backend is later tested against.
"""

import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import (
    ReferencePanic,
    iter_candidates,
    process_word,
    process_word_reverse,
    process_word_substitute_all,
    process_word_substitute_all_reverse,
)


def run(word, table, lo=0, hi=15, **kw):
    return list(iter_candidates(word, table, lo, hi, **kw))


HELLO_TABLE = {b"h": [b"H"], b"e": [b"E"], b"l": [b"L"], b"o": [b"O"]}
PASSWORD_TABLE = {
    b"p": [b"P"], b"a": [b"A"], b"s": [b"S"], b"w": [b"W"],
    b"o": [b"O"], b"r": [b"R"], b"d": [b"D"],
}


class TestDefaultMode:
    def test_keyspace_hello_31(self):
        # Q10: k substitutable single-option positions => 2^k - 1 variants
        assert len(run(b"hello", HELLO_TABLE)) == 31

    def test_keyspace_password_255(self):
        assert len(run(b"password", PASSWORD_TABLE)) == 255

    def test_q1_original_never_emitted(self):
        # min==0 silently bumped to 1 (main.go:169-171)
        out = run(b"ab", {b"a": [b"X"]}, lo=0)
        assert b"ab" not in out
        assert out == [b"Xb"]

    def test_q5_longest_key_first_ordering(self):
        # verified vector: "ss" with {s=Z, ss=ß} => ß, Zs, ZZ, sZ
        out = run(b"ss", {b"s": [b"Z"], b"ss": ["ß".encode()]})
        assert out == ["ß".encode(), b"Zs", b"ZZ", b"sZ"]

    def test_q6_no_rematch_of_replacement(self):
        # verified: ab with a=b,b=c => bb, bc, ac (no cc)
        out = run(b"ab", {b"a": [b"b"], b"b": [b"c"]})
        assert out == [b"bb", b"bc", b"ac"]

    def test_q7_duplicate_options_duplicate_candidates(self):
        out = run(b"a", {b"a": [b"X", b"X"]})
        assert out == [b"X", b"X"]

    def test_q7_convergent_paths_duplicate(self):
        out = run(b"ab", {b"a": [b"X"], b"ab": [b"Xb"]})
        assert sorted(out) == [b"Xb", b"Xb"]

    def test_min_max_window(self):
        out = run(b"hello", HELLO_TABLE, lo=2, hi=2)
        # C(5,2) = 10 pairs of substitutable positions ('l' appears twice)
        assert len(out) == 10
        assert all(sum(b < 0x61 for b in w) == 2 for w in out)

    def test_max_zero_emits_nothing(self):
        assert run(b"hello", HELLO_TABLE, lo=0, hi=0) == []

    def test_multioption_key(self):
        out = run(b"a", {b"a": [b"1", b"2"]})
        assert out == [b"1", b"2"]

    def test_no_match_emits_nothing(self):
        assert run(b"zzz", HELLO_TABLE) == []

    def test_length_changing_sub(self):
        out = run(b"ab", {b"a": [b"XY"]})
        assert out == [b"XYb"]

    def test_empty_key_inert(self):
        # match length >= 1 in default mode: empty key never looked up
        assert run(b"ab", {b"": [b"X"]}) == []

    def test_dfs_order_deterministic(self):
        out1 = run(b"hello", HELLO_TABLE)
        out2 = run(b"hello", HELLO_TABLE)
        assert out1 == out2
        # first emission substitutes the first substitutable position
        assert out1[0] == b"Hello"


class TestReverseMode:
    def test_q1_original_emitted_at_min_zero(self):
        out = run(b"ab", {b"a": [b"X"]}, reverse=True)
        assert b"ab" in out
        assert out == [b"Xb", b"ab"]  # max->min order: 1 sub first, then 0

    def test_q2_first_option_only(self):
        out = run(b"a", {b"a": [b"1", b"2"]}, lo=1, reverse=True)
        assert out == [b"1"]

    def test_q3_offset_bug_reproduced(self):
        # verified vector: "ab" with a=XX, b=YY at exactly 2 subs emits aXXY
        out = run(b"ab", {b"a": [b"XX"], b"b": [b"YY"]}, lo=2, hi=2, reverse=True)
        assert out == [b"aXXY"]

    def test_q3_bug_fixed_mode(self):
        out = run(
            b"ab", {b"a": [b"XX"], b"b": [b"YY"]}, lo=2, hi=2,
            reverse=True, bug_compat=False,
        )
        assert out == [b"XXYY"]

    def test_q3_panic_vector(self):
        # "abab" with ab=X: descending combo [ab@2, ab@0] drives the buggy
        # offset negative => the Go binary panics with slice out of range
        with pytest.raises(ReferencePanic):
            run(b"abab", {b"ab": [b"X"]}, lo=2, hi=2, reverse=True)

    def test_q3_panic_vector_fixed_mode_ok(self):
        out = run(b"abab", {b"ab": [b"X"]}, lo=2, hi=2, reverse=True,
                  bug_compat=False)
        assert out == [b"XX"]

    def test_overlap_filter(self):
        # "ss" spans s@0, ss@0, s@1: subsets of size 2 = {s@0,s@1} only
        out = run(b"ss", {b"s": [b"Z"], b"ss": ["ß".encode()]},
                  lo=2, hi=2, reverse=True)
        assert out == [b"ZZ"]

    def test_early_return_when_too_few_positions(self):
        assert run(b"a", {b"a": [b"X"]}, lo=5, reverse=True) == []

    def test_descending_count_order(self):
        out = run(b"ab", {b"a": [b"A"], b"b": [b"B"]}, reverse=True)
        # combos enumerate by DESCENDING index (main.go:273): among the k=1
        # combos, position 1 ('b') substitutes before position 0 ('a')
        assert out == [b"AB", b"aB", b"Ab", b"ab"]


class TestSubstituteAllMode:
    def test_q1_original_emitted_at_min_zero(self):
        out = run(b"aa", {b"a": [b"X"]}, substitute_all=True)
        assert out == [b"XX", b"aa"]

    def test_all_occurrences_replaced_together(self):
        out = run(b"abab", {b"a": [b"X"]}, lo=1, substitute_all=True)
        assert out == [b"XbXb"]

    def test_count_is_distinct_patterns_not_occurrences(self):
        # "aa" has ONE unique pattern; min=2 can never be met
        assert run(b"aa", {b"a": [b"X"]}, lo=2, substitute_all=True) == []

    def test_product_keyspace(self):
        # Q10: prod(options_i + 1) over unique patterns present
        out = run(b"ab", {b"a": [b"1", b"2"], b"b": [b"3"]}, substitute_all=True)
        assert len(out) == (2 + 1) * (1 + 1)

    def test_enumeration_order(self):
        # first pattern's options first, then skip branch (main.go:349-360)
        out = run(b"ab", {b"a": [b"1"], b"b": [b"2"]}, substitute_all=True)
        assert out == [b"12", b"1b", b"a2", b"ab"]

    def test_q4_canonical_cascade_order(self):
        # a=b then b=c chosen together: sorted order applies a's ReplaceAll
        # first, so its output 'b' is re-replaced by the later b=c pass => cc
        out = run(b"ab", {b"a": [b"b"], b"b": [b"c"]}, lo=2, substitute_all=True)
        assert out == [b"cc"]

    def test_transliteration_full_word(self):
        table = {b"q": ["й".encode()], b"w": ["ц".encode()]}
        out = run(b"qw", table, lo=2, substitute_all=True)
        assert out == ["йц".encode()]

    def test_multichar_pattern(self):
        out = run(b"ssa", {b"ss": ["ß".encode()]}, lo=1, substitute_all=True)
        assert out == ["ßa".encode()]

    def test_empty_key_live_in_substitute_all(self):
        # empty pattern matches every non-empty word; Python bytes.replace
        # inserts per byte (documented divergence for multi-byte runes)
        out = run(b"ab", {b"": [b"-"]}, lo=1, substitute_all=True)
        assert out == [b"-a-b-"]


class TestSubstituteAllReverseMode:
    def test_q1_original_at_min_zero_and_subset_lattice(self):
        out = run(b"ab", {b"a": [b"1"], b"b": [b"2"]},
                  substitute_all=True, reverse=True)
        # full set, then remove-one subsets in index order, down to empty
        assert out == [b"12", b"a2", b"ab", b"1b"]

    def test_q2_first_option_only(self):
        out = run(b"a", {b"a": [b"1", b"2"]}, lo=1,
                  substitute_all=True, reverse=True)
        assert out == [b"1"]

    def test_subset_count(self):
        table = {b"a": [b"1"], b"b": [b"2"], b"c": [b"3"]}
        out = run(b"abc", table, substitute_all=True, reverse=True)
        assert len(out) == 8  # all subsets of 3 patterns

    def test_early_return_too_few_patterns(self):
        assert run(b"a", {b"a": [b"1"]}, lo=3,
                   substitute_all=True, reverse=True) == []

    def test_min_truncates_lattice(self):
        table = {b"a": [b"1"], b"b": [b"2"], b"c": [b"3"]}
        out = run(b"abc", table, lo=2, substitute_all=True, reverse=True)
        assert len(out) == 4  # C(3,3) + C(3,2)

    def test_max_filters_but_descends(self):
        table = {b"a": [b"1"], b"b": [b"2"], b"c": [b"3"]}
        out = run(b"abc", table, lo=0, hi=1, substitute_all=True, reverse=True)
        assert sorted(out) == sorted([b"1bc", b"a2c", b"ab3", b"abc"])


class TestAgainstFixtureTables:
    def test_german_default_mode(self, reference_tables):
        from hashcat_a5_table_generator_tpu.tables.parser import (
            read_substitution_table,
        )

        table = read_substitution_table(str(reference_tables / "german.table"))
        out = list(process_word(b"strasse", table, 0, 15))
        assert "straße".encode() in out
        # span model: substitutable spans are a@3, s@4, s@5, ss@4, e@6 is not
        # in the table; non-overlapping subsets (weighted, all 1 option):
        # positions {a,s,s,ss} -> count = 2^2 * 3 ... verified via keyspace
        from hashcat_a5_table_generator_tpu.oracle.keyspace import (
            count_candidates,
        )

        assert len(out) == count_candidates(b"strasse", table, 0, 15)
        # the ß variants arise via BOTH the multi-char 'ss' key and Z is
        # absent here, so straße appears exactly once
        assert out.count("straße".encode()) == 1

    def test_cyrillic_substitute_all(self, reference_tables):
        from hashcat_a5_table_generator_tpu.tables.parser import (
            read_substitution_table,
        )

        table = read_substitution_table(
            str(reference_tables / "qwerty-cyrillic.table")
        )
        out = list(process_word_substitute_all(b"password", table, 8, 15))
        # p,a,s,w,o,r,d = 7 unique patterns; lo=8 unreachable
        assert out == []
        out = list(process_word_substitute_all(b"password", table, 7, 15))
        assert out == ["зфыыцщкв".encode()]

"""Pair-lane tier (K=2 candidates per hash lane, PERF.md §24).

The pair tier must be STREAM-INVISIBLE: every test here pins the pair
path's results equal to K=1 — hits by full (word_index, rank,
candidate) tuples, candidate buffers byte-for-byte — across the XLA
twin, the Pallas interpret kernels, the superstep drive, sharding, and
resume.  Eligibility edges (odd innermost radix, windowed plans,
bytescan hatch, multi-hash-block widths) must fall back to K=1, never
change the stream.
"""

import hashlib

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    plan_arrays,
    table_arrays,
)
from hashcat_a5_table_generator_tpu.ops import pallas_expand as pe
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
from hashcat_a5_table_generator_tpu.ops.expand_matches import expand_matches
from hashcat_a5_table_generator_tpu.ops.expand_suball import expand_suball
from hashcat_a5_table_generator_tpu.ops.hashes import HASH_FNS
from hashcat_a5_table_generator_tpu.ops.packing import (
    pack_words,
    piece_schema_for,
)
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.runtime import Sweep, SweepConfig
from hashcat_a5_table_generator_tpu.runtime.sinks import HitRecorder
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

#: 1:1 option maps (radix 2 everywhere — even, so pair-eligible).
#: STATIC delta: every value is 2 bytes (partner is +1 byte always).
SUB_STATIC = {b"a": [b"@@"], b"o": [b"00"], b"s": [b"$$"], b"e": [b"33"]}
#: DYNAMIC delta: 1- and 2-byte values mixed (delta 0 or +1 per word).
SUB_DYN = {b"a": [b"@@"], b"o": [b"0"], b"s": [b"$"], b"e": [b"33"]}
#: Odd innermost radix (2 options -> radix 3): pair-INELIGIBLE.
SUB_ODD = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"]}

WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assessor",
         b"ease", b"oases"]


def oracle_lines(spec, sub_map, words):
    out = []
    for w in words:
        out.extend(
            iter_candidates(
                w, sub_map, spec.min_substitute, spec.max_substitute,
                substitute_all=spec.mode.startswith("suball"),
                reverse=spec.mode in ("reverse", "suball-reverse"),
            )
        )
    return out


def hit_tuples(res):
    return [(h.word_index, h.variant_rank, h.candidate) for h in res.hits]


def run_crack(spec, sub_map, words, digests, *, pair, devices=1,
              superstep=None, **cfg_kw):
    cfg = SweepConfig(lanes=64, num_blocks=16, superstep=superstep,
                      devices=devices, pair=pair, **cfg_kw)
    return Sweep(spec, sub_map, words, digests, config=cfg).run_crack()


def _base_rank(plan, batch, b):
    base = 0
    scale = 1
    w = int(batch.word[b])
    for s in range(plan.num_slots):
        base += int(batch.base_digits[b, s]) * scale
        scale *= int(plan.pat_radix[w, s])
    return base


def _xla_stream(spec, ct, plan, *, pair_k, stride, nb):
    """Whole-plan candidate stream via the XLA expand twin: sorted
    (word, rank, bytes) tuples of every emitted candidate."""
    p = plan_arrays(plan)
    t = table_arrays(ct)
    pieces = piece_schema_for(plan, ct)
    rank_stride = stride * (pair_k or 1)
    lanes = nb * stride
    out = []
    w, r = 0, 0
    while w < plan.batch:
        batch, w, r = make_blocks(
            plan, start_word=w, start_rank=r,
            max_variants=nb * rank_stride, max_blocks=nb,
            fixed_stride=rank_stride,
        )
        if not len(batch.count):
            break
        batch = pad_batch(batch, nb)
        b = block_arrays(batch, num_blocks=nb)
        kw = dict(
            num_lanes=lanes, out_width=int(plan.out_width),
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, block_stride=stride,
            radix2=pe.k_opts_for(plan) == 1, pieces=pieces,
            pair_k=pair_k,
        )
        if spec.mode in ("default", "reverse"):
            cand, clen, wr, emit = expand_matches(
                p["tokens"], p["lengths"], p["match_pos"],
                p["match_len"], p["match_radix"], p["match_val_start"],
                t["val_bytes"], t["val_len"],
                b["word"], b["base"], b["count"], b["offset"], **kw,
            )
        else:
            cand, clen, wr, emit = expand_suball(
                p["tokens"], p["lengths"], p["pat_radix"],
                p["pat_val_start"], p["seg_orig_start"],
                p["seg_orig_len"], p["seg_pat"],
                t["val_bytes"], t["val_len"],
                b["word"], b["base"], b["count"], b["offset"], **kw,
            )
        cand = np.asarray(cand)
        clen = np.asarray(clen)
        wr = np.asarray(wr)
        emit = np.asarray(emit)
        bases = [_base_rank(plan, batch, bi) for bi in range(nb)]
        for i in np.nonzero(emit)[0]:
            blk, rin = divmod(int(i), rank_stride)
            out.append((int(wr[i]), bases[blk] + rin,
                        bytes(cand[i, : clen[i]])))
    return sorted(out)


class TestPairGate:
    """Schema-compile pair eligibility pins."""

    def test_even_radix_match_schema_is_eligible(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(SUB_STATIC)
        plan = build_plan(spec, ct, pack_words(WORDS))
        sch = piece_schema_for(plan, ct)
        assert sch.pair_ok
        assert 0 in sch.groups[sch.pair_g0].sel_cols
        # Every value is 2 bytes over 1-byte keys: the partner (chosen)
        # variant is exactly one byte longer than the skip variant.
        assert (sch.pair_dmin, sch.pair_dmax) == (1, 1)

    def test_mixed_value_widths_bound_a_dynamic_delta(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(SUB_DYN)
        plan = build_plan(spec, ct, pack_words(WORDS))
        sch = piece_schema_for(plan, ct)
        assert sch.pair_ok
        assert sch.pair_dmin < sch.pair_dmax

    def test_odd_innermost_radix_is_ineligible(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(SUB_ODD)
        plan = build_plan(spec, ct, pack_words(WORDS))
        sch = piece_schema_for(plan, ct)
        assert not sch.pair_ok

    def test_wrapper_gate_rejects_windowed_and_wide(self):
        ct = compile_table(SUB_STATIC)
        spec = AttackSpec(mode="default", algo="md5", min_substitute=1,
                          max_substitute=1)
        plan = build_plan(spec, ct, pack_words(WORDS))
        pieces = piece_schema_for(plan, ct)
        if getattr(plan, "windowed", False):
            assert pe.pair_for_config(
                spec, plan, pieces, block_stride=64
            ) is None
        # Multi-hash-block widths keep K=1 (nothing idle to amortize).
        spec2 = AttackSpec(mode="default", algo="md5")
        long_words = [bytes(range(97, 123)) * 2 + b"ab"]  # 54 bytes
        plan2 = build_plan(spec2, ct, pack_words(long_words))
        pieces2 = piece_schema_for(plan2, ct)
        assert int(plan2.out_width) > 55
        assert pe.pair_for_config(
            spec2, plan2, pieces2, block_stride=64
        ) is None

    def test_fused_wrapper_raises_on_bypassed_gate(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(SUB_ODD)
        plan = build_plan(spec, ct, pack_words(WORDS))
        p = plan_arrays(plan)
        t = table_arrays(ct)
        batch, _, _ = make_blocks(plan, max_variants=8 * 256,
                                  max_blocks=8, fixed_stride=256)
        b = block_arrays(pad_batch(batch, 8), num_blocks=8)
        with pytest.raises(ValueError, match="pair"):
            pe.fused_expand_md5(
                p["tokens"], p["lengths"], p["match_pos"],
                p["match_len"], p["match_radix"], p["match_val_start"],
                t["val_bytes"], t["val_len"],
                b["word"], b["base"], b["count"],
                num_lanes=8 * 128, out_width=int(plan.out_width),
                min_substitute=spec.effective_min,
                max_substitute=spec.max_substitute, block_stride=128,
                k_opts=pe.k_vals_for(plan), interpret=True,
                pieces=piece_schema_for(plan, ct), pair=True,
            )


class TestXlaPairParity:
    """The XLA twin: pair streams == solo streams, byte for byte."""

    @pytest.mark.parametrize("mode", [
        "default", pytest.param("suball", marks=pytest.mark.slow),
    ])
    @pytest.mark.parametrize("sub", [
        SUB_STATIC, pytest.param(SUB_DYN, marks=pytest.mark.slow),
    ], ids=["static-delta", "dynamic-delta"])
    def test_pair_stream_equals_solo(self, mode, sub):
        spec = AttackSpec(mode=mode, algo="md5")
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(WORDS))
        pieces = piece_schema_for(plan, ct)
        if not pieces.pair_ok:
            pytest.skip("suball schema maps slot 0 off column 0 here")
        solo = _xla_stream(spec, ct, plan, pair_k=None, stride=8, nb=4)
        pair = _xla_stream(spec, ct, plan, pair_k=2, stride=8, nb=4)
        assert solo == pair
        assert len(solo) == len(oracle_lines(spec, sub, WORDS))

    def test_suball_single_occurrence_pattern_pairs(self):
        """A suball schema IS pair-eligible when pattern slot 0 drives
        column 0 and nothing else — one occurrence per word."""
        sub = {b"a": [b"@@"]}
        words = [b"xaz", b"za", b"a", b"zzz", b"qqa"]
        spec = AttackSpec(mode="suball", algo="md5")
        ct = compile_table(sub)
        plan = build_plan(spec, ct, pack_words(words))
        assert piece_schema_for(plan, ct).pair_ok
        solo = _xla_stream(spec, ct, plan, pair_k=None, stride=8, nb=2)
        pair = _xla_stream(spec, ct, plan, pair_k=2, stride=8, nb=2)
        assert solo == pair
        assert len(solo) == len(oracle_lines(spec, sub, words))

    @pytest.mark.slow
    def test_seeded_fuzz_random_words(self):
        rng = np.random.default_rng(7)
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(SUB_DYN)
        for _ in range(2):
            words = [
                bytes(rng.choice(list(b"aoeszx"),
                                 size=rng.integers(1, 9)))
                for _ in range(12)
            ]
            plan = build_plan(spec, ct, pack_words(words))
            solo = _xla_stream(spec, ct, plan, pair_k=None, stride=8,
                               nb=4)
            pair = _xla_stream(spec, ct, plan, pair_k=2, stride=8, nb=4)
            assert solo == pair


class TestPallasInterpretPairParity:
    """The fused piece kernels in interpret mode: pair emit masks and
    digests equal the XLA pair twin for every emitted lane."""

    def test_scalar_tier_matches_xla(self):
        self._check("md5", scalar_units=True)

    def test_general_tier_matches_xla(self):
        self._check("md5", scalar_units=False)

    @pytest.mark.slow
    @pytest.mark.parametrize("algo", ["ntlm", "sha1", "md4"])
    def test_more_algos_match_xla(self, algo):
        self._check(algo, scalar_units=True)

    def _check(self, algo, *, scalar_units):
        spec = AttackSpec(mode="default", algo=algo)
        ct = compile_table(SUB_DYN)
        words = [b"ase", b"oo", b"z", b"seas", b"es"]
        plan = build_plan(spec, ct, pack_words(words))
        pieces = piece_schema_for(plan, ct)
        assert pieces.pair_ok
        p = plan_arrays(plan)
        t = table_arrays(ct)
        stride, nb = 128, 8
        batch, _, _ = make_blocks(plan, max_variants=nb * 2 * stride,
                                  max_blocks=nb, fixed_stride=2 * stride)
        batch = pad_batch(batch, nb)
        b = block_arrays(batch, num_blocks=nb)
        kw = dict(
            num_lanes=nb * stride, out_width=int(plan.out_width),
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, block_stride=stride,
            radix2=True, pieces=pieces, pair_k=2,
        )
        cand, clen, _w, emit = expand_matches(
            p["tokens"], p["lengths"], p["match_pos"], p["match_len"],
            p["match_radix"], p["match_val_start"],
            t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], b["offset"], **kw,
        )
        want_state = np.asarray(HASH_FNS[algo](cand, clen))
        want_emit = np.asarray(emit)
        state, got_emit = pe.fused_expand_md5(
            p["tokens"], p["lengths"], p["match_pos"], p["match_len"],
            p["match_radix"], p["match_val_start"],
            t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"],
            num_lanes=nb * stride, out_width=int(plan.out_width),
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, block_stride=stride,
            k_opts=pe.k_vals_for(plan), algo=algo, interpret=True,
            scalar_units=scalar_units and pe.scalar_units_for(plan),
            pieces=pieces, pair=True,
        )
        state = np.asarray(state)
        got_emit = np.asarray(got_emit)
        assert (got_emit == want_emit).all()
        bad = np.nonzero(want_emit & (state != want_state).any(axis=1))[0]
        assert bad.size == 0, f"digest mismatch at candidate rows {bad[:8]}"


class TestPairSweepParity:
    """End to end through the superstep drive."""

    @pytest.mark.parametrize("sub", [
        SUB_STATIC,
        pytest.param(SUB_DYN, marks=pytest.mark.slow),
    ], ids=["static-delta", "dynamic-delta"])
    def test_pair_on_off_and_per_launch_agree(self, sub):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, sub, WORDS)
        planted = sorted({oracle[0], oracle[len(oracle) // 3],
                          oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        on = run_crack(spec, sub, WORDS, digests, pair=None)
        off = run_crack(spec, sub, WORDS, digests, pair="off")
        solo = run_crack(spec, sub, WORDS, digests, pair=None,
                         superstep=0)
        assert on.superstep["pair"] == 2
        assert off.superstep["pair"] == 0
        assert on.n_emitted == off.n_emitted == solo.n_emitted \
            == len(oracle)
        assert hit_tuples(on) == hit_tuples(off) == hit_tuples(solo)
        assert {h.candidate for h in on.hits} == set(planted)

    def test_ineligible_schema_falls_back_to_solo(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_ODD, WORDS)
        digests = [hashlib.md5(oracle[-1]).digest()]
        res = run_crack(spec, SUB_ODD, WORDS, digests, pair=None)
        assert res.superstep["pair"] == 0  # odd radix: gate refused
        assert res.n_emitted == len(oracle)

    def test_sharded_pair_parity(self):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        planted = sorted({oracle[1], oracle[-1]})
        digests = [hashlib.md5(c).digest() for c in planted]
        one = run_crack(spec, SUB_STATIC, WORDS, digests, pair=None)
        eight = run_crack(spec, SUB_STATIC, WORDS, digests, pair=None,
                          devices=8)
        assert eight.superstep["pair"] == 2
        assert hit_tuples(one) == hit_tuples(eight)
        assert eight.n_emitted == one.n_emitted == len(oracle)

    @pytest.mark.slow
    def test_overflow_replays_through_solo_path(self):
        """A pair superstep whose hit buffer overflows replays its block
        range per-launch (K=1) — hits must still be exact."""
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        digests = [hashlib.md5(c).digest() for c in oracle[:40]]
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=None,
                          superstep_hit_cap=4, pair=None)
        res = Sweep(spec, SUB_STATIC, WORDS, digests,
                    config=cfg).run_crack()
        assert res.superstep["pair"] == 2
        assert res.superstep["replays"] >= 1
        want = run_crack(spec, SUB_STATIC, WORDS, digests, pair="off",
                         superstep=0)
        assert hit_tuples(res) == hit_tuples(want)
        assert res.n_hits == 40


class TestPairPacked:
    """The pair tier through the resident engine's packed dispatch."""

    @pytest.mark.slow
    def test_packed_pair_tenants_byte_parity(self):
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from tests.test_engine import cfg, full_hits

        spec = AttackSpec(mode="default", algo="md5")
        lists = [WORDS, WORDS[::-1]]
        jobs = []
        for i, words in enumerate(lists):
            oracle = oracle_lines(spec, SUB_STATIC, words)
            digests = [hashlib.md5(oracle[0]).digest(),
                       hashlib.md5(oracle[-1]).digest(),
                       hashlib.md5(b"tenant-%d" % i).digest()]
            jobs.append((words, digests))
        c = cfg(superstep=2)
        want = [
            Sweep(spec, SUB_STATIC, w, d, config=c).run_crack(
                resume=False
            )
            for w, d in jobs
        ]
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, SUB_STATIC, w, d) for w, d in jobs]
        eng.run_until_idle()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        for g, w in zip(got, want):
            assert g.superstep.get("packed") == 2
            assert g.superstep.get("pair") == 2
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted

    @pytest.mark.slow
    def test_fuse_build_worker_death_restarts_once(self):
        """A WorkerDeath during the off-thread fuse build restarts the
        admission worker once and re-runs the SAME batch — the tenants
        still fuse and stay byte-identical to solo (the job-build
        path's recovery, extended to the fuse seam)."""
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from hashcat_a5_table_generator_tpu.runtime.faults import (
            WorkerDeath,
        )
        from tests.test_engine import cfg, full_hits

        spec = AttackSpec(mode="default", algo="md5")
        jobs = []
        for words in (WORDS, WORDS[::-1]):
            o = oracle_lines(spec, SUB_STATIC, words)
            jobs.append((words, [hashlib.md5(o[0]).digest(),
                                 hashlib.md5(o[-1]).digest()]))
        c = cfg(superstep=2)
        want = [
            Sweep(spec, SUB_STATIC, w, d, config=c).run_crack(
                resume=False
            )
            for w, d in jobs
        ]
        eng = Engine(c, auto=False)
        orig = eng._prepare_fuse
        fired = []

        def dying(slots):
            if not fired:
                fired.append(True)
                raise WorkerDeath("injected fuse-build death")
            return orig(slots)

        eng._prepare_fuse = dying
        handles = [eng.submit(spec, SUB_STATIC, w, d) for w, d in jobs]
        eng.run_until_idle()
        got = [h.result(timeout=5) for h in handles]
        eng.close()
        assert fired
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.superstep.get("packed") == 2

    @pytest.mark.slow
    def test_pair_and_solo_configs_never_fuse(self):
        from hashcat_a5_table_generator_tpu.runtime.engine import Engine
        from tests.test_engine import cfg, full_hits

        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        digests = [hashlib.md5(oracle[-1]).digest()]
        c_on = cfg(superstep=2)
        c_off = cfg(superstep=2, pair=0)
        want = Sweep(spec, SUB_STATIC, WORDS, digests,
                     config=c_on).run_crack(resume=False)
        eng = Engine(c_on, auto=False)
        h1 = eng.submit(spec, SUB_STATIC, WORDS, digests)
        h2 = eng.submit(spec, SUB_STATIC, WORDS, digests, config=c_off)
        eng.run_until_idle()
        g1, g2 = h1.result(timeout=0), h2.result(timeout=0)
        eng.close()
        # Disagreeing pair knobs = different static programs: neither
        # packs with the other, both streams stay exact.
        assert g1.superstep.get("packed") is None
        assert g2.superstep.get("packed") is None
        assert g1.superstep.get("pair") == 2
        assert g2.superstep.get("pair") == 0
        assert full_hits(g1) == full_hits(g2) == full_hits(want)


class TestPairResume:
    """Checkpoints are (word, rank) cursors — pair and solo runs resume
    each other's checkpoints byte-exactly."""

    @pytest.mark.parametrize("first_pair,second_pair", [
        (None, "off"),
        pytest.param("off", None, marks=pytest.mark.slow),
    ], ids=["pair-to-solo", "solo-to-pair"])
    def test_cross_tier_resume(self, tmp_path, first_pair, second_pair):
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        # A LATE planted hit: several superstep boundaries (and
        # checkpoints, every_s=0) pass before the recorder explodes, so
        # the resumed run really starts mid-sweep.
        planted = sorted({oracle[-2]})
        digests = [hashlib.md5(c).digest() for c in planted]
        want = run_crack(spec, SUB_STATIC, WORDS, digests, pair=None)

        path = str(tmp_path / "pair.json")
        cfg = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                          pair=first_pair, checkpoint_path=path,
                          checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                raise Boom()

        first = Sweep(spec, SUB_STATIC, WORDS, digests, config=cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())

        cfg2 = SweepConfig(lanes=64, num_blocks=16, superstep=1,
                           pair=second_pair, checkpoint_path=path,
                           checkpoint_every_s=0.0)
        got = Sweep(spec, SUB_STATIC, WORDS, digests,
                    config=cfg2).run_crack()
        assert got.resumed
        assert sorted(h.candidate for h in got.hits) == sorted(
            h.candidate for h in want.hits
        )
        # A cross-tier resume must stay on the SUPERSTEP executor —
        # a K=1 checkpoint misaligned for K=2 degrades to the K=1
        # superstep tier, never to the per-launch path.
        assert got.superstep.get("supersteps", 0) >= 1


@pytest.mark.slow
def test_pair_ab_record_shape():
    """bench --pair-ab end to end at toy scale: one JSON line with the
    per-arm instruments, pair engaged, parity enforced by the bench."""
    import json
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    out = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--pair-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "64", "--seconds", "1"],
        capture_output=True, text=True, timeout=600,
        cwd=str(repo),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "pair_lane_ab"
    assert rec["pair_k"] == 2
    assert rec["solo"]["emitted_per_sweep"] == \
        rec["pair"]["emitted_per_sweep"]
    assert rec["pair"]["dispatches_per_sweep"] <= \
        rec["solo"]["dispatches_per_sweep"]
    for arm in ("solo", "pair"):
        assert rec[arm]["hashes_per_sec"] > 0
        assert rec[arm]["ops_per_candidate"]
    assert 0 < rec["eligibility_share"] <= 1.0


class TestPairEscapeHatches:
    def test_env_off_disables_pair(self, monkeypatch):
        monkeypatch.setenv("A5GEN_PAIR", "off")
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        digests = [hashlib.md5(oracle[0]).digest()]
        res = run_crack(spec, SUB_STATIC, WORDS, digests, pair=None)
        assert res.superstep["pair"] == 0
        assert res.n_emitted == len(oracle)

    def test_env_typo_warns_and_keeps_default(self, monkeypatch, capsys):
        import hashcat_a5_table_generator_tpu.runtime.env as env

        monkeypatch.setenv("A5GEN_PAIR", "offf")
        monkeypatch.setattr(env, "_WARNED", set())
        assert env.pair_enabled()  # typo keeps the default (on)
        err = capsys.readouterr().err
        assert "A5GEN_PAIR" in err and "offf" in err
        # once per value
        assert env.pair_enabled()
        assert "A5GEN_PAIR" not in capsys.readouterr().err

    def test_bytescan_hatch_keeps_k1(self, monkeypatch):
        monkeypatch.setenv("A5GEN_EMIT", "bytescan")
        spec = AttackSpec(mode="default", algo="md5")
        oracle = oracle_lines(spec, SUB_STATIC, WORDS)
        digests = [hashlib.md5(oracle[0]).digest()]
        res = run_crack(spec, SUB_STATIC, WORDS, digests, pair=None)
        # No piece schema under bytescan -> no pair tier, same stream.
        assert res.superstep["pair"] == 0
        assert res.n_emitted == len(oracle)

"""End-to-end CLI tests: reference-compatible flag surface, oracle and
device backends, crack mode, emit-table, error paths (SURVEY.md §4.5)."""

import hashlib
import json
import subprocess
import sys

import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.tables.parser import load_tables

#: In-process devices are forced onto CPU by conftest; subprocesses need the
#: same (the axon plugin ignores JAX_PLATFORMS env, so use jax.config).
DRIVER = (
    "import sys\n"
    "try:\n"
    "    import jax\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "except ImportError:\n"
    "    pass  # oracle-path tests must run in a jax-less environment\n"
    "from hashcat_a5_table_generator_tpu.cli import main\n"
    "sys.exit(main(sys.argv[1:]))"
)


def run_cli(*argv, check=True):
    r = subprocess.run(
        [sys.executable, "-c", DRIVER, *argv], capture_output=True
    )
    if check and r.returncode != 0:
        raise AssertionError(
            f"CLI failed ({r.returncode}):\n{r.stderr.decode()[-2000:]}"
        )
    return r


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    (d / "dict.txt").write_bytes(b"password\nsesame\nzzz\n")
    (d / "leet.table").write_bytes(b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n")
    return d


def oracle_all(sub_map, words, mn=0, mx=15, suball=False, reverse=False):
    out = []
    for w in words:
        out.extend(
            iter_candidates(w, sub_map, mn, mx,
                            substitute_all=suball, reverse=reverse)
        )
    return out


class TestReferenceSurface:
    def test_default_mode_matches_oracle_in_order(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"))
        sub = load_tables([str(workdir / "leet.table")])
        want = oracle_all(sub, [b"password", b"sesame", b"zzz"])
        assert r.stdout.splitlines() == want  # exact --threads 1 order

    def test_all_four_modes(self, workdir):
        sub = load_tables([str(workdir / "leet.table")])
        for flags, kw in [
            ((), {}),
            (("-r",), dict(reverse=True)),
            (("-s",), dict(suball=True)),
            (("-s", "-r"), dict(suball=True, reverse=True)),
        ]:
            r = run_cli(str(workdir / "dict.txt"),
                        "-t", str(workdir / "leet.table"), *flags)
            want = oracle_all(sub, [b"password", b"sesame", b"zzz"], **kw)
            assert r.stdout.splitlines() == want, flags

    def test_min_max_window(self, workdir):
        sub = load_tables([str(workdir / "leet.table")])
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "-m", "2", "-x", "3")
        want = oracle_all(sub, [b"password", b"sesame", b"zzz"], mn=2, mx=3)
        assert r.stdout.splitlines() == want

    def test_merged_tables_append_options(self, workdir, tmp_path):
        extra = tmp_path / "extra.table"
        extra.write_bytes(b"a=AAA\n")
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "-t", str(extra))
        sub = load_tables([str(workdir / "leet.table"), str(extra)])
        assert sub[b"a"] == [b"4", b"@", b"AAA"]
        want = oracle_all(sub, [b"password", b"sesame", b"zzz"])
        assert r.stdout.splitlines() == want

    def test_threads_flag_accepted(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--threads", "8")
        assert r.returncode == 0


class TestErrors:
    def test_missing_table_flag(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), check=False)
        assert r.returncode == 2
        assert b"table-files" in r.stderr

    def test_min_above_max(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "-m", "5", "-x", "2", check=False)
        assert r.returncode == 2

    def test_oversized_line_rejected_not_truncated(self, workdir, tmp_path):
        # Anti-Q8: the reference silently ends input here with exit 0.
        big = tmp_path / "big.txt"
        big.write_bytes(b"x" * 100 + b"\n")
        r = run_cli(str(big), "-t", str(workdir / "leet.table"),
                    "--max-word-bytes", "50", check=False)
        assert r.returncode != 0

    def test_bad_digest_file(self, workdir, tmp_path):
        bad = tmp_path / "bad.hashes"
        bad.write_bytes(b"zznothex\n")
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--digests", str(bad), check=False)
        assert r.returncode != 0
        assert b"not a hex digest" in r.stderr


class TestEmitTable:
    def test_emit_stdout_round_trips(self):
        r = run_cli("--emit-table", "german")
        assert r.stdout == (
            b"A=\xc3\xa4\nO=\xc3\xb6\nU=\xc3\xbc\na=\xc3\xa4\no=\xc3\xb6\n"
            b"u=\xc3\xbc\nss=\xc3\x9f\nZ=\xc3\x9f\n"
        )

    def test_emit_matches_upstream_artifact(self, upstream_reference):
        got = run_cli("--emit-table", "qwerty-cyrillic").stdout
        want = (upstream_reference / "qwerty-cyrillic.table").read_bytes()
        assert got == want

    def test_list_layouts(self):
        r = run_cli("--list-layouts")
        names = [l.split(b"\t")[0] for l in r.stdout.splitlines()]
        assert b"qwerty-cyrillic" in names
        assert b"azerty-qwerty" in names  # derived, not checked in upstream

    def test_unknown_layout(self):
        r = run_cli("--emit-table", "dvorak-klingon", check=False)
        assert r.returncode != 0


class TestDeviceBackend:
    def test_zero_pair_table(self, workdir, tmp_path):
        # A table whose every line is skipped (comments / no '=') compiles
        # to zero value rows; the device sweep must agree with the oracle
        # (no candidates under the Q1 min bump), not crash in a gather.
        empty = tmp_path / "empty.table"
        empty.write_bytes(b"# nothing here\nnot a pair\n")
        outs = [
            run_cli(str(workdir / "dict.txt"), "-t", str(empty),
                    "--backend", be, "--lanes", "256", "--blocks", "16")
            for be in ("device", "oracle")
        ]
        assert outs[0].stdout == outs[1].stdout == b""

    def test_candidates_multiset_parity(self, workdir):
        sub = load_tables([str(workdir / "leet.table")])
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--lanes", "256", "--blocks", "16")
        from collections import Counter

        want = Counter(oracle_all(sub, [b"password", b"sesame", b"zzz"]))
        assert Counter(r.stdout.splitlines()) == want

    def test_crack_mode_finds_planted(self, workdir, tmp_path):
        sub = load_tables([str(workdir / "leet.table")])
        plant = oracle_all(sub, [b"sesame"])[5]
        hashes = tmp_path / "t.hashes"
        hashes.write_bytes(
            hashlib.md5(plant).hexdigest().encode() + b"\n"
            + hashlib.md5(b"decoy").hexdigest().encode() + b"\n"
        )
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--digests", str(hashes),
                    "--lanes", "256", "--blocks", "16")
        lines = r.stdout.splitlines()
        assert lines == [hashlib.md5(plant).hexdigest().encode() + b":" + plant]
        assert b"1 hits" in r.stderr

    def test_bug_compat_reverse_routes_to_oracle(self, workdir, tmp_path):
        # Length-changing table (1 byte -> 2 bytes) exposes the Q3 offset
        # bug; --backend device --bug-compat -r must yield the ORACLE's
        # bug-exact bytes, with a loud warning.
        t = tmp_path / "grow.table"
        t.write_bytes(b"a=XX\nb=YY\n")
        d = tmp_path / "d.txt"
        d.write_bytes(b"ab\n")
        dev = run_cli(str(d), "-t", str(t), "-r", "--bug-compat",
                      "--backend", "device")
        orc = run_cli(str(d), "-t", str(t), "-r", "--bug-compat",
                      "--backend", "oracle")
        assert dev.stdout == orc.stdout
        assert b"routing" in dev.stderr and b"oracle" in dev.stderr
        # The Q3 vector itself: exactly-2-subs on "ab" emits the corrupted
        # aXXY, not the corrected XXYY (SURVEY.md Q3).
        exact = run_cli(str(d), "-t", str(t), "-r", "--bug-compat",
                        "-m", "2", "-x", "2", "--backend", "device")
        assert exact.stdout == b"aXXY\n"

    def test_bug_compat_non_reverse_warns_no_effect(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t",
                    str(workdir / "leet.table"), "--backend", "device",
                    "--bug-compat", "--lanes", "256", "--blocks", "16")
        assert b"no effect" in r.stderr
        assert r.stdout  # sweep still ran

    def test_devices_sharded_stream_identical(self, workdir):
        base = (str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                "--backend", "device", "--lanes", "64", "--blocks", "16")
        single = run_cli(*base, "--devices", "1")
        multi = run_cli(*base, "--devices", "8")
        auto = run_cli(*base, "--devices", "auto")
        # Sharded + each explicit layout (auto resolves to stride for
        # this divisible geometry).
        strided = run_cli(*base, "--devices", "8",
                          "--block-layout", "stride")
        assert multi.stdout == single.stdout
        assert auto.stdout == single.stdout
        assert strided.stdout == single.stdout
        assert single.stdout  # non-empty stream

    def test_devices_rejects_garbage(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t",
                    str(workdir / "leet.table"), "--backend", "device",
                    "--devices", "lots", check=False)
        assert r.returncode != 0
        assert b"--devices" in r.stderr

    def test_buckets_mixed_length_dictionary(self, workdir, tmp_path):
        # Explicit bucketing: an over-the-last-boundary line must not break
        # the sweep (it gets its own bucket width), parity holds per word,
        # and the reorder notice appears (mixed-length stream, candidates
        # mode).
        d = tmp_path / "mixed.txt"
        long_word = b"q" * 68 + b"as"
        d.write_bytes(b"password\n" + long_word + b"\nzzz\n")
        sub = load_tables([str(workdir / "leet.table")])
        r = run_cli(str(d), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--buckets", "16,32,64",
                    "--lanes", "256", "--blocks", "16")
        from collections import Counter

        want = Counter(oracle_all(sub, [b"password", long_word, b"zzz"]))
        assert Counter(r.stdout.splitlines()) == want
        assert b"reorders" in r.stderr

    def test_candidates_default_strict_order(self, workdir, tmp_path):
        # Candidates mode defaults to --buckets none: a mixed-length
        # dictionary streams in strict word order (no bucket-major
        # permutation), diffable against the oracle, with no notice.
        d = tmp_path / "mixed_order.txt"
        words = [b"password", b"q" * 20 + b"as", b"zzz"]
        d.write_bytes(b"\n".join(words) + b"\n")
        sub = load_tables([str(workdir / "leet.table")])
        r = run_cli(str(d), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--lanes", "256", "--blocks", "16")
        got = r.stdout.splitlines()
        want = oracle_all(sub, words)
        # Per-word multiset parity AND global word order: candidates from
        # word i all precede candidates from word j>i.
        assert sorted(got) == sorted(want)
        from collections import Counter

        pos = 0
        for w in words:
            per_word = Counter(oracle_all(sub, [w]))
            n = sum(per_word.values())
            assert Counter(got[pos:pos + n]) == per_word
            pos += n
        assert b"reorders" not in r.stderr

    def test_buckets_none_single_width(self, workdir):
        base = (str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                "--backend", "device", "--lanes", "256", "--blocks", "16")
        bucketed = run_cli(*base, "--buckets", "16,32,64")
        single = run_cli(*base, "--buckets", "none")
        auto = run_cli(*base, "--buckets", "auto")
        assert sorted(bucketed.stdout.splitlines()) == sorted(
            single.stdout.splitlines()
        )
        # 'auto' in candidates mode = none: byte-identical strict order.
        assert auto.stdout == single.stdout

    def test_crack_default_still_bucketed(self, workdir, tmp_path):
        # Crack mode keeps the bucketed default: the checkpoint FILE is a
        # bucket manifest, not a legacy single-file cursor.
        d = tmp_path / "mixed_crack.txt"
        d.write_bytes(b"password\n" + b"q" * 20 + b"as\nzzz\n")
        target = hashlib.md5(b"p4ssword").hexdigest()
        dig = tmp_path / "digs.txt"
        dig.write_text(target + "\n")
        ck = tmp_path / "crack_ck.json"
        r = run_cli(str(d), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--digests", str(dig),
                    "--checkpoint", str(ck),
                    "--lanes", "256", "--blocks", "16")
        assert b"p4ssword" in r.stdout
        manifest = json.loads(ck.read_text())
        assert "buckets" in manifest  # top-level manifest => bucketed run

    def test_buckets_rejects_garbage(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t",
                    str(workdir / "leet.table"), "--backend", "device",
                    "--buckets", "64,16", check=False)
        assert r.returncode != 0
        assert b"--buckets" in r.stderr

    def test_retries_candidates_requires_checkpoint(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t",
                    str(workdir / "leet.table"), "--backend", "device",
                    "--retries", "2", check=False)
        assert r.returncode != 0
        assert b"--checkpoint" in r.stderr

    def test_retry_machinery_resumes_and_dedupes(self):
        # Library-level: _run_with_retries re-invokes with resume=True after
        # a failure; _DedupRecorder suppresses cross-attempt hit replays.
        from hashcat_a5_table_generator_tpu.cli import (
            _DedupRecorder,
            _run_with_retries,
        )
        from hashcat_a5_table_generator_tpu.runtime.sinks import HitRecord

        calls = []

        def attempt(resume):
            calls.append(resume)
            if len(calls) < 3:
                raise RuntimeError("chip fell over")
            return "done"

        assert _run_with_retries(
            attempt, 5, default_resume=False, label="t"
        ) == "done"
        # First attempt honors the caller default (--no-resume); retries
        # force resume=True regardless.
        assert calls == [False, True, True]

        with pytest.raises(RuntimeError):
            _run_with_retries(
                lambda _: (_ for _ in ()).throw(RuntimeError("x")),
                1, default_resume=True, label="t",
            )

        class Sink:
            def __init__(self):
                self.got = []

            def emit(self, rec):
                self.got.append(rec)

        sink = Sink()
        rec = _DedupRecorder(sink)
        h = HitRecord(word_index=3, variant_rank=7, candidate=b"x",
                      digest_hex="00")
        rec.emit(h)
        rec.emit(h)  # the retry's resume replay
        rec.emit(HitRecord(word_index=3, variant_rank=8, candidate=b"y",
                           digest_hex="01"))
        assert len(sink.got) == 2

    def test_crack_unbucketed_single_sweep(self, workdir, tmp_path):
        # --digests with --buckets none reaches plain Sweep.run_crack with
        # the CLI's dedup recorder directly (no bucketed _ForwardRecorder
        # shield) — regression: the wrapper must expose .hits.
        target = hashlib.md5(b"p4ssword").hexdigest()
        dig = tmp_path / "digs_nb.txt"
        dig.write_text(target + "\n")
        r = run_cli(str(workdir / "dict.txt"), "-t",
                    str(workdir / "leet.table"), "--backend", "device",
                    "--digests", str(dig), "--buckets", "none",
                    "--lanes", "256", "--blocks", "16")
        assert b"p4ssword" in r.stdout
        assert b"1 hits" in r.stderr

    def test_block_layouts_stream_identical(self, workdir):
        # Force BOTH layouts explicitly (auto resolves to stride for this
        # divisible geometry, so flagless-vs-stride would compare stride to
        # itself): stride and packed must produce byte-identical streams.
        base = (str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                "--backend", "device", "--lanes", "64", "--blocks", "16")
        strided = run_cli(*base, "--block-layout", "stride")
        packed = run_cli(*base, "--block-layout", "packed")
        auto = run_cli(*base)
        assert packed.stdout == strided.stdout == auto.stdout
        assert strided.stdout

    @pytest.mark.slow  # ~10 s on the tier-1 host (jax profiler
    # start/stop dominates); the CLI device-backend plumbing keeps
    # default coverage via the other TestDeviceBackend arms.
    def test_profile_writes_trace(self, workdir, tmp_path):
        # --profile DIR: a device sweep leaves a jax.profiler trace on disk
        # (plugins/profile/<ts>/*.trace.json.gz or *.xplane.pb, backend-
        # dependent) — the one observability flag must actually observe.
        trace_dir = tmp_path / "trace"
        r = run_cli(str(workdir / "dict.txt"),
                    "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--profile", str(trace_dir),
                    "--lanes", "256", "--blocks", "16")
        assert r.stdout  # sweep still streamed candidates
        files = [p for p in trace_dir.rglob("*") if p.is_file()]
        assert files, "profile dir exists but holds no trace artifacts"

    def test_progress_lines(self, workdir):
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--backend", "device", "--progress",
                    "--lanes", "256", "--blocks", "16")
        prog = [json.loads(l) for l in r.stderr.decode().splitlines()
                if '"progress"' in l]
        assert prog and prog[-1]["progress"]["words"] == [3, 3]

    def test_checkpoint_written_and_resume_skips(self, workdir, tmp_path):
        ck = tmp_path / "ck.json"
        args = (str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                "--backend", "device", "--checkpoint", str(ck),
                "--lanes", "256", "--blocks", "16")
        r1 = run_cli(*args)
        assert ck.exists()
        assert r1.stdout  # full candidate stream
        r2 = run_cli(*args)  # complete checkpoint -> nothing re-emitted
        assert r2.stdout == b""
        r3 = run_cli(*args, "--no-resume")
        assert r3.stdout == r1.stdout or sorted(r3.stdout.splitlines()) == sorted(
            r1.stdout.splitlines()
        )


class TestOracleCrack:
    def test_oracle_backend_crack(self, workdir, tmp_path):
        sub = load_tables([str(workdir / "leet.table")])
        plant = oracle_all(sub, [b"password"])[0]
        hashes = tmp_path / "t.hashes"
        hashes.write_bytes(hashlib.md5(plant).hexdigest().encode() + b"\n")
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "--digests", str(hashes))
        assert r.stdout.splitlines() == [
            hashlib.md5(plant).hexdigest().encode() + b":" + plant
        ]

    def test_ntlm_crack(self, workdir, tmp_path):
        from hashcat_a5_table_generator_tpu.utils.md4 import ntlm

        sub = load_tables([str(workdir / "leet.table")])
        plant = oracle_all(sub, [b"zzz"], suball=True)[0]  # original word
        hashes = tmp_path / "t.hashes"
        hashes.write_bytes(ntlm(plant).hex().encode() + b"\n")
        r = run_cli(str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
                    "-s", "--algo", "ntlm", "--digests", str(hashes))
        assert r.stdout.splitlines() == [
            ntlm(plant).hex().encode() + b":" + plant
        ]


def test_fetch_chunk_flag(workdir, tmp_path):
    # --fetch-chunk reaches the sweep config; a chunk of 1 must still find
    # every planted hit (per-launch fetching, the pre-chunking behavior).
    sub = load_tables([str(workdir / "leet.table")])
    cand = next(iter_candidates(b"password", sub, 0, 15))
    digests = tmp_path / "d.txt"
    digests.write_text(hashlib.md5(cand).hexdigest() + "\n")
    for chunk in ("1", "64"):
        r = run_cli(
            str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
            "--backend", "device", "--digests", str(digests),
            "--algo", "md5", "--fetch-chunk", chunk,
            "--lanes", "64", "--blocks", "16",
        )
        assert hashlib.md5(cand).hexdigest().encode() in r.stdout, chunk


def test_fetch_chunk_rejects_nonpositive(workdir):
    r = run_cli(
        str(workdir / "dict.txt"), "-t", str(workdir / "leet.table"),
        "--backend", "device", "--fetch-chunk", "0", check=False,
    )
    assert r.returncode != 0
    assert b"positive integer" in r.stderr

"""End-to-end diff against the compiled Go reference binary (SURVEY.md §4.5).

No Go toolchain ships in this image, so these tests are gated on
``A5GEN_REFERENCE_BIN`` — the path to a compiled ``a5_generator`` binary
(``go build`` in /root/reference, ``README.MD:186-189``).  Unset, every test
skips cleanly; set, the harness

* **byte-diffs** the oracle backend's stdout against the binary run with
  ``--threads 1`` (deterministic global order: words in file order, variants
  in DFS order — SURVEY.md Q9), and
* **multiset-diffs** the device backend's stdout per run (the device
  enumerates rank order within each word, a documented divergence —
  ops/expand_matches.py).

The binary's CLI surface is the kong struct at ``main.go:18-26``:
positional DICT, -t/--table-files, -m/--table-min, -x/--table-max,
--threads, -s/--substitute-all, -r/--reverse-sub.
"""

import os
import subprocess
import sys
from collections import Counter

import pytest

from hashcat_a5_table_generator_tpu.runtime.env import read_env

REFERENCE_BIN = read_env("A5GEN_REFERENCE_BIN")

pytestmark = pytest.mark.skipif(
    not REFERENCE_BIN or not os.path.isfile(REFERENCE_BIN),
    reason="A5GEN_REFERENCE_BIN not set (compiled Go reference unavailable)",
)

DRIVER = (
    "import sys\n"
    "try:\n"
    "    import jax\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "except ImportError:\n"
    "    pass\n"
    "from hashcat_a5_table_generator_tpu.cli import main\n"
    "sys.exit(main(sys.argv[1:]))"
)

#: (flags, reverse-mode?) — all four engines plus count windows.
MODE_MATRIX = [
    ((), False),
    (("-m", "2", "-x", "3"), False),
    (("-r",), True),
    (("-r", "-m", "0", "-x", "2"), True),
    (("-s",), False),
    (("-s", "-m", "1", "-x", "2"), False),
    (("-s", "-r"), True),
]


@pytest.fixture(scope="module")
def corpus(tmp_path_factory, reference_tables):
    d = tmp_path_factory.mktemp("refbin")
    dict_file = d / "dict.txt"
    dict_file.write_bytes(
        b"password\nhello\nstrasse\nss\nab\nzzz\nq,q\nmotdepasse\n"
    )
    tables = [
        str(reference_tables / "german.table"),
        str(reference_tables / "qwerty-azerty.table"),
    ]
    return dict_file, tables


def run_reference(dict_file, tables, flags):
    argv = [REFERENCE_BIN, str(dict_file), "--threads", "1"]
    for t in tables:
        argv += ["-t", t]
    argv += list(flags)
    r = subprocess.run(argv, capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    return r.stdout


def run_ours(dict_file, tables, flags, backend, *, bug_compat=None):
    argv = [sys.executable, "-c", DRIVER, str(dict_file)]
    for t in tables:
        argv += ["-t", t]
    argv += ["--backend", backend, *flags]
    if backend == "device":
        argv += ["--lanes", "4096", "--blocks", "64"]
    if bug_compat is None:
        # Byte-exact parity with the binary's reverse engine requires its
        # Q3 offset arithmetic (main.go:249-257); the tables here are
        # length-changing (ss=ß), so the oracle opts in by default.  The
        # device plan deliberately emits corrected offsets instead
        # (--bug-compat with --backend device would reroute to the oracle).
        bug_compat = backend == "oracle" and "-r" in flags and "-s" not in flags
    if bug_compat:
        argv += ["--bug-compat"]
    r = subprocess.run(argv, capture_output=True, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    return r.stdout


@pytest.mark.parametrize("flags,_rev", MODE_MATRIX,
                         ids=lambda v: " ".join(v) if isinstance(v, tuple) else None)
def test_oracle_stdout_byte_exact(corpus, flags, _rev):
    dict_file, tables = corpus
    want = run_reference(dict_file, tables, flags)
    got = run_ours(dict_file, tables, flags, "oracle")
    assert got == want


@pytest.mark.parametrize("flags,_rev", MODE_MATRIX,
                         ids=lambda v: " ".join(v) if isinstance(v, tuple) else None)
def test_device_stdout_multiset(corpus, flags, _rev):
    dict_file, tables = corpus
    want = Counter(run_reference(dict_file, tables, flags).splitlines())
    if "-r" in flags and "-s" not in flags:
        # The device reverse plan emits corrected offsets (no Q3 bug) and
        # no oracle fallback applies — compare against the corrected oracle
        # instead of the binary for length-changing tables.
        corrected = run_ours(dict_file, tables, tuple(flags), "oracle",
                             bug_compat=False)
        want = Counter(corrected.splitlines())
    got = Counter(run_ours(dict_file, tables, flags, "device").splitlines())
    assert got == want

"""Multi-host runtime: stripe math in-process; the distributed path as a
real 2-process CPU job (jax.distributed over a localhost coordinator) —
SURVEY.md §4.3's fake-device pattern extended to processes (VERDICT r1 #4).

The child processes each see ONE local CPU device; the parent asserts
process 0's combined hit set equals a single-process sweep's.
"""

import hashlib
import json
import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.parallel.multihost import (
    host_stripe,
    stripe_packed,
)

REPO = pathlib.Path(__file__).resolve().parent.parent

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"xyzzy", b"sass"]


class TestStripes:
    def test_stripes_partition_exactly(self):
        for n in (0, 1, 7, 8, 9, 100):
            for procs in (1, 2, 3, 8):
                spans = [host_stripe(n, procs, p) for p in range(procs)]
                # Contiguous, ordered, disjoint, covering.
                assert spans[0][0] == 0
                assert spans[-1][1] == n
                for (a, b), (c, d) in zip(spans, spans[1:]):
                    assert b == c
                sizes = [hi - lo for lo, hi in spans]
                assert max(sizes) - min(sizes) <= 1

    def test_stripe_preserves_global_index(self):
        packed = pack_words(WORDS)
        lo, hi = host_stripe(len(WORDS), 2, 1)
        part = stripe_packed(packed, lo, hi)
        assert part.words() == WORDS[lo:hi]
        assert list(part.index) == list(range(lo, hi))

    def test_bad_process_id_raises(self):
        with pytest.raises(ValueError):
            host_stripe(10, 2, 2)


def test_initialize_no_cluster_falls_back_single_process(tmp_path):
    """All-None initialize() on a plain host (no TPU pod / SLURM / MPI env)
    reports single-process instead of raising."""
    script = tmp_path / "solo.py"
    script.write_text(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "for v in ('SLURM_JOB_ID', 'OMPI_COMM_WORLD_SIZE'):\n"
        "    os.environ.pop(v, None)\n"
        "from hashcat_a5_table_generator_tpu.parallel import multihost\n"
        "assert multihost.initialize() == (0, 1)\n"
        "print('OK')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr.decode()[-3000:]
    assert b"OK" in r.stdout


_CHILD = r"""
import json, os, sys

pid = int(sys.argv[1])
port = sys.argv[2]
outdir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # one local device per process

import jax

jax.config.update("jax_platforms", "cpu")

# Exercise multihost.initialize() itself (advisor r2, medium: it used to
# probe jax.process_count() first, which spun up the XLA backend and made
# jax.distributed.initialize unconditionally fail).
from hashcat_a5_table_generator_tpu.parallel import multihost

topo = multihost.initialize(f"127.0.0.1:{port}", 2, pid)
assert topo == (pid, 2), topo
# Idempotent: a second call reports the live topology.
assert multihost.initialize() == (pid, 2)
assert jax.process_count() == 2

import hashlib
from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.parallel.multihost import (
    run_crack_multihost,
)
from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"xyzzy", b"sass"]
digests = [bytes.fromhex(h) for h in json.loads(sys.argv[4])]

spec = AttackSpec(mode="default", algo="md5")
res = run_crack_multihost(
    spec, LEET, pack_words(WORDS), digests,
    config=SweepConfig(lanes=64, num_blocks=16),
)
with open(os.path.join(outdir, f"out{pid}.json"), "w") as fh:
    json.dump({
        "n_emitted": res.n_emitted,
        "n_hits": res.n_hits,
        "resumed": res.resumed,
        "wall_s": res.wall_s,
        "hits": [
            [h.word_index, h.variant_rank, h.candidate.hex(), h.digest_hex]
            for h in res.hits
        ],
    }, fh)
"""


def test_two_process_crack_matches_single(tmp_path, pod_collectives):
    # Single-process expectation via the ordinary sweep.
    from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
    from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig

    spec = AttackSpec(mode="default", algo="md5")
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, LEET, 0, 15))
    # Plant hits on both halves of the wordlist so both stripes find some.
    planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
    digests = [hashlib.md5(c).digest() for c in planted]
    digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(20)]

    want = Sweep(
        spec, LEET, WORDS, digests, config=SweepConfig(lanes=64, num_blocks=16)
    ).run_crack()
    want_hits = [
        [h.word_index, h.variant_rank, h.candidate.hex(), h.digest_hex]
        for h in sorted(want.hits, key=lambda h: (h.word_index, h.variant_rank))
    ]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    digest_arg = json.dumps([d.hex() for d in digests])
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(p), str(port), str(tmp_path),
             digest_arg],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]

    results = [
        json.load(open(tmp_path / f"out{p}.json")) for p in range(2)
    ]
    # Both processes hold the SAME combined result — resumed/wall_s are
    # globally reduced (any/max), not host-local (advisor r2).
    assert results[0] == results[1]
    assert results[0]["resumed"] is False
    assert results[0]["hits"] == want_hits
    assert results[0]["n_emitted"] == want.n_emitted == len(oracle)
    assert {bytes.fromhex(h[2]) for h in results[0]["hits"]} == set(planted)


def test_two_process_cli_crack_matches_single(tmp_path, pod_collectives):
    """The CLI pod surface (VERDICT r3 #3): two ``a5gen`` subprocesses with
    --coordinator/--num-processes/--process-id produce (on process 0's
    stdout) exactly the hit set a single-process run finds."""
    import hashlib

    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates

    leet_lines = b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n"
    table = tmp_path / "leet.table"
    table.write_bytes(leet_lines)
    dict_file = tmp_path / "dict.txt"
    dict_file.write_bytes(b"\n".join(WORDS) + b"\n")

    sub = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, sub, 0, 15))
    planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
    digests_file = tmp_path / "digests.txt"
    digests_file.write_bytes(
        b"".join(hashlib.md5(c).digest().hex().encode() + b"\n"
                 for c in planted)
    )

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # one local CPU device per process
    env["JAX_PLATFORMS"] = "cpu"
    driver = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hashcat_a5_table_generator_tpu.cli import main\n"
        "sys.exit(main(sys.argv[1:]))"
    )
    base = [
        sys.executable, "-c", driver, str(dict_file), "-t", str(table),
        "--backend", "device", "--digests", str(digests_file),
        "--lanes", "64", "--blocks", "16",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
    ]
    procs = [
        subprocess.Popen(base + ["--process-id", str(p)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]

    # Process 0 reports every planted hit exactly once; process 1 emits no
    # hit lines (the gloo CPU backend noisily prints "[Gloo] Rank ..." to
    # stdout during init — match hit lines by their 32-hex:plain shape).
    def hit_lines(out):
        return [
            line for line in out.splitlines()
            if len(line.split(b":", 1)[0]) == 32
            and not line.startswith(b"[Gloo]")
        ]

    stdout0, stderr0 = outs[0]
    assert hit_lines(outs[1][0]) == []
    got_plains = sorted(
        line.split(b":", 1)[1] for line in hit_lines(stdout0)
    )
    assert got_plains == planted
    assert b"distributed process 0/2" in stderr0
    assert f"{len(planted)} hits".encode() in stderr0


def test_initialize_explicit_single_process_is_noop():
    """initialize(num_processes=1) with no coordinator short-circuits to
    (0, 1) without touching jax.distributed (regression: the r3 rework
    briefly made this raise ValueError)."""
    from hashcat_a5_table_generator_tpu.parallel import multihost

    assert multihost.initialize(num_processes=1) == (0, 1)
    assert multihost.initialize(process_id=0) == (0, 1)


@pytest.mark.slow  # multi-process pod kill/recovery: ~100 s of subprocess barriers
def test_peer_loss_survivor_aborts_loudly_then_resumes(tmp_path):
    """VERDICT r4 #6: kill one of two processes mid-sweep; the survivor
    must exit LOUDLY (nonzero, resume instructions on stderr) instead of
    hanging in the hit all-gather — and a healthy pod relaunch with the
    same --checkpoint must resume and find every planted hit."""
    import hashlib

    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates

    table = tmp_path / "leet.table"
    table.write_bytes(b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n")
    dict_file = tmp_path / "dict.txt"
    dict_file.write_bytes(b"\n".join(WORDS) + b"\n")

    sub = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, sub, 0, 15))
    planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
    digests_file = tmp_path / "digests.txt"
    digests_file.write_bytes(
        b"".join(hashlib.md5(c).digest().hex().encode() + b"\n"
                 for c in planted)
    )
    ckpt = tmp_path / "sweep.ckpt"

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # one local CPU device per process
    env["JAX_PLATFORMS"] = "cpu"
    env["A5GEN_DCN_TIMEOUT"] = "20"

    driver = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hashcat_a5_table_generator_tpu.cli import main\n"
        "sys.exit(main(sys.argv[1:]))"
    )
    # The dying peer: joins the pod, completes backend init (so the
    # survivor's own init can finish), then dies without a trace.
    dying = (
        "import os, sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hashcat_a5_table_generator_tpu.parallel import multihost\n"
        "multihost.initialize(sys.argv[1], 2, 1)\n"
        "jax.devices()\n"
        "import time; time.sleep(3)\n"
        "os._exit(0)\n"
    )

    def cli_args(port, process_id):
        return [
            str(dict_file), "-t", str(table),
            "--backend", "device", "--digests", str(digests_file),
            "--lanes", "64", "--blocks", "16",
            "--checkpoint", str(ckpt),
            "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
            "--process-id", str(process_id),
        ]

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    # --- phase 1: process 1 dies mid-sweep; process 0 must abort loudly.
    port = free_port()
    survivor = subprocess.Popen(
        [sys.executable, "-c", driver] + cli_args(port, 0),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    peer = subprocess.Popen(
        [sys.executable, "-c", dying, f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    peer.communicate(timeout=120)
    out0, err0 = survivor.communicate(timeout=180)  # not hanging IS the test
    assert survivor.returncode == 3, (survivor.returncode,
                                      err0.decode()[-3000:])
    assert b"FATAL" in err0
    assert b"relaunch the pod" in err0
    # The survivor checkpointed its stripe before the abort.
    assert (tmp_path / "sweep.ckpt.p0").exists()

    # --- phase 2: healthy relaunch with the same checkpoint resumes and
    # reports every planted hit.
    port = free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", driver] + cli_args(port, p),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]

    def hit_lines(out):
        return [
            line for line in out.splitlines()
            if len(line.split(b":", 1)[0]) == 32
            and not line.startswith(b"[Gloo]")
        ]

    got_plains = sorted(
        line.split(b":", 1)[1] for line in hit_lines(outs[0][0])
    )
    assert got_plains == planted


@pytest.mark.slow  # deliberately slow peer: ~25 s wall
def test_slow_peer_does_not_trip_failure_detector(tmp_path):
    """A STRAGGLER is not a dead peer: with the detection threshold far
    below the straggler's delay, the waiting process must keep waiting
    (the peer's heartbeat stays live) and the pod must complete."""
    import hashlib

    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates

    table = tmp_path / "leet.table"
    table.write_bytes(b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n")
    dict_file = tmp_path / "dict.txt"
    dict_file.write_bytes(b"\n".join(WORDS) + b"\n")
    sub = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, sub, 0, 15))
    planted = sorted({oracle[0], oracle[-1]})
    digests_file = tmp_path / "digests.txt"
    digests_file.write_bytes(
        b"".join(hashlib.md5(c).digest().hex().encode() + b"\n"
                 for c in planted)
    )

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # Threshold (8s) far below the straggler's sleep (20s): only the
    # heartbeat keeps process 0 from a spurious PeerLossError.
    env["A5GEN_DCN_TIMEOUT"] = "8"

    driver = (
        "import sys, time\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "pid = int(sys.argv[1])\n"
        "if pid == 1:\n"
        "    from hashcat_a5_table_generator_tpu.parallel import multihost\n"
        "    multihost.initialize(sys.argv[2], 2, 1)\n"
        "    time.sleep(20)  # straggle AFTER joining (heartbeat running)\n"
        "from hashcat_a5_table_generator_tpu.cli import main\n"
        "sys.exit(main(sys.argv[3:]))"
    )
    cli = [
        str(dict_file), "-t", str(table),
        "--backend", "device", "--digests", str(digests_file),
        "--lanes", "64", "--blocks", "16",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", driver, str(p), f"127.0.0.1:{port}"]
            + cli + ["--process-id", str(p)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, (p.returncode, err.decode()[-3000:])

    def hit_lines(out):
        return [
            line for line in out.splitlines()
            if len(line.split(b":", 1)[0]) == 32
            and not line.startswith(b"[Gloo]")
        ]

    got_plains = sorted(
        line.split(b":", 1)[1] for line in hit_lines(outs[0][0])
    )
    assert got_plains == planted


@pytest.mark.slow  # elastic 3-process pod: ~60 s of subprocess barriers
def test_pod_hits_local_is_elastic_and_union_complete(tmp_path):
    """--pod-hits local: (a) two healthy hosts each report exactly their
    own stripe's hits and the union equals the single-host hit set;
    (b) a peer dying mid-run cannot block the survivor — it completes
    its stripe and exits 0 (no collectives exist to hang in)."""
    import hashlib

    from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates

    table = tmp_path / "leet.table"
    table.write_bytes(b"a=4\na=@\no=0\ns=$\ns=5\ne=3\n")
    dict_file = tmp_path / "dict.txt"
    dict_file.write_bytes(b"\n".join(WORDS) + b"\n")
    sub = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
    oracle = []
    for w in WORDS:
        oracle.extend(iter_candidates(w, sub, 0, 15))
    planted = sorted({oracle[0], oracle[len(oracle) // 2], oracle[-1]})
    digests_file = tmp_path / "digests.txt"
    digests_file.write_bytes(
        b"".join(hashlib.md5(c).digest().hex().encode() + b"\n"
                 for c in planted)
    )

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["A5GEN_DCN_TIMEOUT"] = "30"  # must never fire: no collectives

    driver = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hashcat_a5_table_generator_tpu.cli import main\n"
        "sys.exit(main(sys.argv[1:]))"
    )

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def cli_args(port, pid):
        return [
            str(dict_file), "-t", str(table),
            "--backend", "device", "--digests", str(digests_file),
            "--lanes", "64", "--blocks", "16", "--pod-hits", "local",
            "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
            "--process-id", str(pid),
        ]

    def hit_lines(out):
        return [
            line for line in out.splitlines()
            if len(line.split(b":", 1)[0]) == 32
            and not line.startswith(b"[Gloo]")
        ]

    # (a) healthy pod: per-host streams, union == single-host hit set.
    port = free_port()
    procs = [
        subprocess.Popen([sys.executable, "-c", driver] + cli_args(port, p),
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]
    per_host = [sorted(line.split(b":", 1)[1] for line in hit_lines(o))
                for o, _ in outs]
    assert sorted(per_host[0] + per_host[1]) == planted
    assert per_host[0] and per_host[1]  # hits planted on both stripes
    assert b"stripe:" in outs[0][1]

    # (b) peer dies after joining: the survivor still completes cleanly.
    port = free_port()
    dying = (
        "import os, sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from hashcat_a5_table_generator_tpu.parallel import multihost\n"
        "multihost.initialize(sys.argv[1], 2, 1)\n"
        "jax.devices()\n"
        "import time; time.sleep(2)\n"
        "os._exit(0)\n"
    )
    survivor = subprocess.Popen(
        [sys.executable, "-c", driver] + cli_args(port, 0),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    peer = subprocess.Popen(
        [sys.executable, "-c", dying, f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    peer.communicate(timeout=120)
    out0, err0 = survivor.communicate(timeout=180)
    assert survivor.returncode == 0, (survivor.returncode,
                                      err0.decode()[-3000:])
    got = sorted(line.split(b":", 1)[1] for line in hit_lines(out0))
    assert got == per_host[0]  # its whole stripe, nothing blocked

"""Elastic fleet (PERF.md §27): autoscaling, admission control,
backpressure, and the health ladder.

Fast tier runs STUB engines (fake links with scripted request/scrape
replies — no jax, no sockets) so the control-plane contracts are
deterministic and cheap: typed overload rejection with
``retry_after_s``, shed policies (reject / oldest / queue) with
deadline-carrying jobs first, per-tenant in-flight caps, bounded
router memory under sustained overload, pending dispatch as capacity
frees, the healthy→degraded→quarantined ladder with placement
exclusion, capture-time checkpoint validation, autoscaler hysteresis +
cooldown, and the three §27 fault seams (``router.place``,
``link.send``, ``engine.spawn``).

The REAL multi-process contracts are slow-marked: the forced
scale-up/scale-down smoke and the elastic chaos soak (seeded engine
kills during autoscale churn, byte-exact per-tenant parity vs solo,
bounded queue growth).
"""

import os
import signal
import threading
import time

import pytest

from hashcat_a5_table_generator_tpu.runtime import faults, telemetry
from hashcat_a5_table_generator_tpu.runtime.autoscale import (
    AutoscaleConfig,
    Autoscaler,
)
from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
    CheckpointCorrupt,
    CheckpointState,
    SweepCursor,
    state_to_doc,
)
from hashcat_a5_table_generator_tpu.runtime.fleet import (
    EngineLink,
    FleetError,
    FleetOverloaded,
    FleetRouter,
    spawn_engines,
)
from tests.test_fleet import (
    BIG_WORDS,
    WORDS,
    _Collector,
    cfg,
    event_hits,
    job_doc,
    planted_digests,
    solo_hits,
)


def wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        time.sleep(0.01)


class FakeLink:
    """A scripted engine link: accepts every request instantly,
    answers health scrapes from ``stats_reply`` — the router's full
    admission/ladder surface with zero device work."""

    def __init__(self, engine_id, index):
        self.engine_id = engine_id
        self.endpoint = f"fake://{engine_id}"
        self.index = index
        self.alive = True
        self.draining = False
        self.health = "healthy"
        self.strikes = 0
        self.clean = 0
        self.replay_fails = 0
        self.ladder_prev = {}
        self.next_poll = 0.0
        self.misses = 0
        self.scrape = {}
        self.routed = set()
        self.requests = []
        self.sent = []
        self.stats_reply = {"event": "stats"}
        self.proc = None
        self._closing = False

    def request(self, doc, timeout=None):
        self.requests.append(doc)
        return {"id": doc.get("id"), "event": "accepted",
                "kind": "crack"}

    def send(self, doc):
        self.sent.append(doc)

    def health_request(self, doc, timeout=None):
        return dict(self.stats_reply)

    def kill_socket(self):
        self.alive = False

    def close(self):
        self.alive = False


def make_router(n_links=1, **kw):
    kw.setdefault("poll_s", 0)
    router = FleetRouter(**kw)
    links = [FakeLink(f"e{i}", i) for i in range(n_links)]
    router._links = links
    return router, links


def collector():
    events = []
    return events, events.append


# ---------------------------------------------------------------------------
# Admission control + backpressure
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_capacity_gates_then_queues_with_queued_ack(self):
        router, (link,) = make_router(engine_capacity=1)
        try:
            ack1 = router.submit({"id": "j1", "digest_list": []})
            assert ack1["engine"] == "e0" and "queued" not in ack1
            ack2 = router.submit({"id": "j2", "digest_list": []})
            assert ack2["queued"] is True and ack2["engine"] is None
            assert router.pending_depth() == 1
            assert link.routed == {"j1"}
        finally:
            router.close(shutdown_engines=False)

    def test_overload_rejects_typed_with_retry_after(self):
        router, (link,) = make_router(
            engine_capacity=1, max_pending=1
        )
        try:
            router.submit({"id": "j1", "digest_list": []})
            router.submit({"id": "j2", "digest_list": []})
            with pytest.raises(FleetOverloaded) as exc:
                router.submit({"id": "j3", "digest_list": []})
            assert exc.value.retry_after_s > 0
            ev = exc.value.event("j3")
            assert ev["error"] == "overloaded"
            assert ev["retry_after_s"] == exc.value.retry_after_s
            assert ev["id"] == "j3"
            # The rejected id is retryable: no stale table entry.
            assert "j3" not in router._jobs
            assert router.stats()["fleet"]["jobs_rejected"] == 1
        finally:
            router.close(shutdown_engines=False)

    def test_pending_dispatches_as_capacity_frees(self):
        router, (link,) = make_router(engine_capacity=1)
        try:
            router.submit({"id": "j1", "digest_list": []})
            events, emit = collector()
            router.submit({"id": "j2", "digest_list": []}, emit=emit)
            assert router.pending_depth() == 1
            # j1 finishes engine-side: the freed slot pumps j2 out.
            router._on_job_event(link, {"id": "j1", "event": "done"})
            wait_for(lambda: "j2" in link.routed, what="j2 placed")
            assert router.pending_depth() == 0
            assert router.job("j2").state == "routed"
        finally:
            router.close(shutdown_engines=False)

    def test_shed_policy_oldest_evicts_to_admit(self):
        router, (link,) = make_router(
            engine_capacity=1, max_pending=1, shed_policy="oldest"
        )
        try:
            router.submit({"id": "j1", "digest_list": []})
            events, emit = collector()
            router.submit({"id": "old", "digest_list": []}, emit=emit)
            ack = router.submit({"id": "new", "digest_list": []})
            assert ack["queued"] is True
            # The old pending job was shed typed, overload-shaped.
            (failed,) = [e for e in events
                         if e.get("event") == "failed"]
            assert failed["error"] == "overloaded"
            assert failed["retry_after_s"] > 0
            assert router.job("old").state == "failed"
            assert [j.id for j in router._pending] == ["new"]
            assert router.stats()["fleet"]["jobs_shed"] == 1
        finally:
            router.close(shutdown_engines=False)

    def test_deadline_carriers_shed_first(self):
        router, (link,) = make_router(
            engine_capacity=1, max_pending=2, shed_policy="oldest"
        )
        try:
            router.submit({"id": "j1", "digest_list": []})
            events, emit = collector()
            # Older job WITHOUT a deadline, newer one WITH: the
            # deadline carrier is the victim despite being newer.
            router.submit({"id": "nodl", "digest_list": []})
            router.submit({"id": "dl", "digest_list": [],
                           "deadline_s": 60.0}, emit=emit)
            router.submit({"id": "spill", "digest_list": []})
            assert router.job("dl").state == "failed"
            assert any(e.get("error") == "overloaded" for e in events)
            assert [j.id for j in router._pending] == ["nodl", "spill"]
        finally:
            router.close(shutdown_engines=False)

    def test_expired_deadline_sheds_at_pump(self):
        router, (link,) = make_router(engine_capacity=1)
        try:
            router.submit({"id": "j1", "digest_list": []})
            events, emit = collector()
            router.submit({"id": "dl", "digest_list": [],
                           "deadline_s": 0.01}, emit=emit)
            time.sleep(0.05)
            router._pump_pending()
            assert router.job("dl").state == "failed"
            (failed,) = [e for e in events
                         if e.get("event") == "failed"]
            assert failed["error"] == "overloaded"
            assert "deadline" in failed["reason"]
        finally:
            router.close(shutdown_engines=False)

    def test_queue_policy_is_the_unbounded_escape_hatch(self):
        router, (link,) = make_router(
            engine_capacity=1, max_pending=1, shed_policy="queue"
        )
        try:
            router.submit({"id": "j0", "digest_list": []})
            for i in range(5):
                ack = router.submit(
                    {"id": f"q{i}", "digest_list": []}
                )
                assert ack["queued"] is True
            assert router.pending_depth() == 5
        finally:
            router.close(shutdown_engines=False)

    def test_per_tenant_cap_rejects_typed(self):
        router, (link,) = make_router(per_tenant=1)
        try:
            router.submit({"id": "t1", "digest_list": [],
                           "tenant": "alice"})
            with pytest.raises(FleetOverloaded) as exc:
                router.submit({"id": "t2", "digest_list": [],
                               "tenant": "alice"})
            assert "alice" in str(exc.value)
            # Other tenants (and tenant-less docs) are unaffected.
            router.submit({"id": "t3", "digest_list": [],
                           "tenant": "bob"})
            router.submit({"id": "t4", "digest_list": []})
            # A settled job releases the slot.
            router._on_job_event(link, {"id": "t1", "event": "done"})
            router.submit({"id": "t5", "digest_list": [],
                           "tenant": "alice"})
        finally:
            router.close(shutdown_engines=False)

    def test_router_memory_bounded_under_sustained_overload(self):
        """The §27 acceptance pin: hammering an overloaded router
        grows NEITHER the pending queue past max_pending NOR the job
        table — rejected ids leave no residue."""
        router, (link,) = make_router(
            engine_capacity=1, max_pending=4
        )
        try:
            router.submit({"id": "j1", "digest_list": []})
            rejected = 0
            for i in range(100):
                try:
                    router.submit({"id": f"burst{i}",
                                   "digest_list": []})
                except FleetOverloaded:
                    rejected += 1
            assert rejected == 96
            assert router.pending_depth() == 4
            # Table: 1 routed + 4 pending — no rejected residue.
            assert len(router._jobs) == 5
        finally:
            router.close(shutdown_engines=False)

    def test_resume_under_overload_keeps_paused_job(self):
        """A rejected RESUME must not destroy the admitted job: it
        stays paused with its checkpoint (the replay origin) intact,
        and the retry succeeds once capacity frees."""
        router, (link,) = make_router(engine_capacity=1, max_pending=0)
        try:
            ckdoc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(0, 4),
                n_emitted=3, n_hits=0, hits=[], wall_s=0.1,
            ))
            router.submit({"id": "p1", "digest_list": []})
            router._on_job_event(link, {
                "id": "p1", "event": "paused", "checkpoint": ckdoc,
            })
            assert router.job("p1").state == "paused"
            router.submit({"id": "run", "digest_list": []})
            with pytest.raises(FleetOverloaded):
                router.resume("p1")
            job = router.job("p1")  # still known — id NOT forgotten
            assert job.state == "paused"
            assert job.checkpoint == ckdoc
            router._on_job_event(link, {"id": "run", "event": "done"})
            ack = router.resume("p1")
            assert ack["resumed"] is True
        finally:
            router.close(shutdown_engines=False)

    def test_resume_retry_while_queued_is_idempotent(self):
        """A client retrying a queued resume (the pattern
        retry_after_s invites) must never double-admit: one pending
        entry, one eventual dispatch."""
        router, (link,) = make_router(engine_capacity=1, max_pending=4)
        try:
            ckdoc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(0, 4),
                n_emitted=3, n_hits=0, hits=[], wall_s=0.1,
            ))
            router.submit({"id": "p1", "digest_list": []})
            router._on_job_event(link, {
                "id": "p1", "event": "paused", "checkpoint": ckdoc,
            })
            router.submit({"id": "run", "digest_list": []})
            ack1 = router.resume("p1")
            ack2 = router.resume("p1")
            assert ack1["queued"] is True and ack2["queued"] is True
            with router._lock:
                pending_ids = [j.id for j in router._pending]
            assert pending_ids.count("p1") == 1
            router._on_job_event(link, {"id": "run", "event": "done"})
            wait_for(lambda: "p1" in link.routed, what="p1 placed")
        finally:
            router.close(shutdown_engines=False)

    def test_dispatch_refuses_already_bound_job(self):
        """Two dispatchers racing one id (concurrent resumes) must not
        double-bind: the second bind fails typed, the first placement
        keeps running."""
        router, (link,) = make_router()
        try:
            router.submit({"id": "j1", "digest_list": []})
            job = router.job("j1")
            assert job.link is link
            with pytest.raises(FleetError) as exc:
                router._dispatch(job)
            assert "already bound" in str(exc.value)
            assert job.link is link  # the running placement survives
        finally:
            router.close(shutdown_engines=False)

    def test_bind_time_capacity_recheck_closes_toctou(self):
        """Two concurrent submits can both pass _pick's capacity test;
        the bind under the lock must re-verify so the cap never
        overshoots — simulated by pinning _pick to a full engine."""
        router, (link,) = make_router(engine_capacity=1)
        try:
            router.submit({"id": "j1", "digest_list": []})
            router._pick = lambda token, exclude=(): link  # the race
            ack = router.submit({"id": "j2", "digest_list": []})
            assert ack["queued"] is True
            assert link.routed == {"j1"}  # never overshot
        finally:
            router.close(shutdown_engines=False)

    def test_cancel_of_pending_job_settles_inline(self):
        router, (link,) = make_router(engine_capacity=1)
        try:
            router.submit({"id": "j1", "digest_list": []})
            events, emit = collector()
            router.submit({"id": "q", "digest_list": []}, emit=emit)
            router.cancel("q")
            assert router.job("q").state == "cancelled"
            assert router.pending_depth() == 0
            assert any(e.get("event") == "cancelled" for e in events)
        finally:
            router.close(shutdown_engines=False)


# ---------------------------------------------------------------------------
# Health ladder + circuit breaking
# ---------------------------------------------------------------------------


class TestHealthLadder:
    def _router2(self, **kw):
        kw.setdefault("degrade_after", 1)
        kw.setdefault("quarantine_after", 3)
        kw.setdefault("recover_after", 2)
        return make_router(n_links=2, **kw)

    def test_rising_demotions_degrade_then_recover(self):
        router, (a, b) = self._router2()
        try:
            a.stats_reply = {"event": "stats", "group_demotions": 0,
                             "job_restarts": 0}
            router._scrape(a, observe=True)  # baseline
            assert a.health == "healthy"
            a.stats_reply["group_demotions"] = 1
            router._scrape(a, observe=True)  # rising delta = strain
            assert a.health == "degraded"
            # Degraded engines place last: a fresh submit avoids it.
            router.submit({"id": "j1", "digest_list": []})
            assert router.job("j1").link is b
            # Two clean scrapes walk it back to healthy.
            router._scrape(a, observe=True)
            assert a.health == "degraded"
            router._scrape(a, observe=True)
            assert a.health == "healthy"
        finally:
            router.close(shutdown_engines=False)

    def test_sustained_strain_quarantines_and_excludes(self):
        router, (a, b) = self._router2(quarantine_after=2)
        make_scaler(router, min_engines=1, max_engines=4)
        try:
            a.stats_reply = {"event": "stats", "group_demotions": 0}
            router._scrape(a, observe=True)
            for i in (1, 2):
                a.stats_reply["group_demotions"] = i
                router._scrape(a, observe=True)
            assert a.health == "quarantined"
            assert router.stats()["fleet"]["engines_quarantined"] == 1
            # No placements land on it, ever (one-way circuit).
            for i in range(4):
                router.submit({"id": f"q{i}", "digest_list": []})
                assert router.job(f"q{i}").link is b
            # A quarantined-only pool is OVERLOAD (replacement is on
            # the way), not absence: submits queue bounded + typed
            # instead of failing with an untyped 'no live engine'.
            b.alive = False
            ack = router.submit({"id": "during", "digest_list": []})
            assert ack["queued"] is True
        finally:
            router.close(shutdown_engines=False)

    def test_fixed_pool_never_quarantines_tops_out_degraded(self):
        """Without an autoscaler there is no replacer: the ladder must
        stop at degraded (place-last) — permanently bricking live
        capacity would be worse, and the poll watchdog still kills
        truly wedged engines."""
        router, (a, b) = self._router2(quarantine_after=2)
        try:
            a.stats_reply = {"event": "stats", "group_demotions": 0}
            router._scrape(a, observe=True)
            for i in (1, 2, 3, 4):
                a.stats_reply["group_demotions"] = i
                router._scrape(a, observe=True)
            assert a.health == "degraded"  # never quarantined
            # Still placeable as the last resort.
            b.alive = False
            router.submit({"id": "last", "digest_list": []})
            assert router.job("last").link is a
        finally:
            router.close(shutdown_engines=False)

    def test_repeated_crash_replays_quarantine(self):
        router, (a, b) = self._router2(
            quarantine_replays=2, replay_budget=5
        )
        make_scaler(router, min_engines=1, max_engines=4)
        try:
            ckdoc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(0, 10),
                n_emitted=5, n_hits=0, hits=[], wall_s=0.1,
            ))
            for i in range(2):
                router.submit({"id": f"r{i}", "digest_list": []})
                job = router.job(f"r{i}")
                wait_for(lambda: job.link is not None,
                         what="placed")
                link = job.link
                link_events = {"id": job.id, "event": "failed",
                               "error": "boom", "checkpoint": ckdoc}
                router._on_job_event(link, link_events)
                wait_for(lambda: job.link is not link or not
                         job.unsettled, what="replayed")
            assert a.replay_fails + b.replay_fails >= 2
            assert "quarantined" in (a.health, b.health)
        finally:
            router.close(shutdown_engines=False)

    def test_replay_fails_decay_on_clean_poll_tick(self):
        """quarantine_replays means failures bunched within one health
        window: a clean observed scrape resets the count, so an
        engine with one recovered transient per week never
        circuit-breaks."""
        router, (a, b) = self._router2(quarantine_replays=2,
                                       replay_budget=5)
        make_scaler(router, min_engines=1, max_engines=4)
        try:
            ckdoc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(0, 2),
                n_emitted=1, n_hits=0, hits=[], wall_s=0.1,
            ))
            router.submit({"id": "r0", "digest_list": []})
            link = router.job("r0").link
            router._on_job_event(link, {
                "id": "r0", "event": "failed", "error": "boom",
                "checkpoint": ckdoc,
            })
            assert link.replay_fails == 1
            link.stats_reply = {"event": "stats"}
            router._scrape(link, observe=True)  # clean poll tick
            assert link.replay_fails == 0
            assert link.health != "quarantined"
        finally:
            router.close(shutdown_engines=False)

    def test_client_stats_scrapes_do_not_feed_ladder(self):
        """Quarantine timing belongs to the POLL cadence: a client
        hammering the stats op must neither rush strikes nor mask
        strain by resetting them between poll ticks."""
        router, (a, b) = self._router2()
        try:
            a.stats_reply = {"event": "stats", "group_demotions": 0}
            router._scrape(a, observe=True)  # poll baseline
            a.stats_reply["group_demotions"] = 5
            for _ in range(4):
                router.stats()  # client-driven scrapes: no ladder
            assert a.health == "healthy"
            router._scrape(a, observe=True)  # the poll tick sees it
            assert a.health == "degraded"
        finally:
            router.close(shutdown_engines=False)

    def test_failed_scrape_strikes_ladder(self):
        router, (a, b) = self._router2(quarantine_after=1)
        make_scaler(router, min_engines=1, max_engines=4)
        try:

            def boom(doc, timeout=None):
                # The real EngineLink wraps transport errors typed.
                raise FleetError("scrape torn")

            a.health_request = boom
            with pytest.raises(FleetError):
                router._scrape(a)
            # The poll loop counts the strike after its in-poll retry;
            # simulate its failure path directly.
            router._ladder_strike(a)
            assert a.health == "quarantined"
        finally:
            router.close(shutdown_engines=False)


# ---------------------------------------------------------------------------
# Capture-time checkpoint validation
# ---------------------------------------------------------------------------


class TestCheckpointCapture:
    def test_malformed_migrate_in_fails_submit_typed(self):
        router, _ = make_router()
        try:
            with pytest.raises(CheckpointCorrupt) as exc:
                router.submit({"id": "m1", "digest_list": [],
                               "checkpoint": {"fingerprint": "fp"}})
            assert "missing required field" in str(exc.value)
            assert "m1" not in router._jobs  # id retryable
        finally:
            router.close(shutdown_engines=False)

    def test_wrong_wire_major_fails_submit_typed(self):
        from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
            CheckpointWireIncompatible,
        )

        router, _ = make_router()
        try:
            doc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(0, 1),
                n_emitted=1, n_hits=0, hits=[], wall_s=0.0,
            ))
            doc["wire_version"] = "9.0"
            with pytest.raises(CheckpointWireIncompatible):
                router.submit({"id": "m2", "digest_list": [],
                               "checkpoint": doc})
        finally:
            router.close(shutdown_engines=False)

    def test_malformed_pause_checkpoint_fails_typed_at_capture(self):
        router, (link,) = make_router()
        try:
            events, emit = collector()
            router.submit({"id": "p1", "digest_list": []}, emit=emit)
            router._on_job_event(link, {
                "id": "p1", "event": "paused",
                "checkpoint": {"fingerprint": "fp"},  # malformed
            })
            assert router.job("p1").state == "failed"
            (failed,) = [e for e in events
                         if e.get("event") == "failed"]
            assert "CheckpointCorrupt" in failed["error"]
            assert "pause" in failed["error"]
        finally:
            router.close(shutdown_engines=False)

    def test_malformed_quarantine_token_not_replayed(self):
        router, (link,) = make_router(replay_budget=3)
        try:
            events, emit = collector()
            router.submit({"id": "f1", "digest_list": []}, emit=emit)
            router._on_job_event(link, {
                "id": "f1", "event": "failed", "error": "boom",
                "checkpoint": {"cursor": {}},  # malformed
            })
            # No requeue: the failure surfaced typed instead.
            assert router.job("f1").state == "failed"
            (failed,) = [e for e in events
                         if e.get("event") == "failed"]
            assert "checkpoint_invalid" in failed
            assert router.job("f1").replays == 0
        finally:
            router.close(shutdown_engines=False)


# ---------------------------------------------------------------------------
# Autoscaler: hysteresis, cooldown, quarantine replacement, reap
# ---------------------------------------------------------------------------


class StubRouter:
    """The autoscaler-facing router surface, scripted."""

    def __init__(self, links=(), pending=0):
        self.links = list(links)
        self.pending = pending
        self.autoscaler = None
        self.drained = []
        self.detached = []
        self.attached = []

    def pending_depth(self):
        return self.pending

    def engines(self):
        return list(self.links)

    def _resolve(self, eid):
        for link in self.links:
            if link.engine_id == eid:
                return link
        raise FleetError(f"unknown engine {eid!r}")

    def drain(self, eid):
        link = self._resolve(eid)
        link.draining = True
        self.drained.append(eid)
        return {"event": "draining", "engine": eid}

    def detach(self, eid, *, shutdown=True, timeout=30.0):
        link = self._resolve(eid)
        if link.routed:
            raise FleetError("still routed")
        self.links.remove(link)
        self.detached.append(eid)

    def attach(self, endpoint, engine_id, *, proc=None, timeout=180.0):
        link = FakeLink(engine_id, len(self.links))
        self.links.append(link)
        self.attached.append(engine_id)
        return link


def make_scaler(router, **cfg_kw):
    cfg_kw.setdefault("interval_s", 0)  # manual ticks
    cfg_kw.setdefault("cooldown_s", 1000.0)
    n = [0]

    def spawner():
        n[0] += 1
        return (f"fake://spawn{n[0]}", f"spawn{n[0]}", None)

    scaler = Autoscaler(router, spawner, AutoscaleConfig(**cfg_kw))
    return scaler


class TestAutoscaler:
    def test_scale_up_needs_sustained_window(self):
        link = FakeLink("e0", 0)
        link.routed = {"a", "b", "c"}
        router = StubRouter([link], pending=2)
        scaler = make_scaler(router, min_engines=1, max_engines=3,
                             scale_up_at=2.0, up_window=2)
        scaler.tick()  # streak 1: no action yet (hysteresis)
        assert router.attached == []
        scaler.tick()  # streak 2: spawn
        assert router.attached == ["spawn1"]
        # Cooldown: sustained pressure cannot spawn again yet.
        scaler.tick()
        scaler.tick()
        assert router.attached == ["spawn1"]
        assert scaler.describe()["cooling_down"] is True

    def test_dead_band_resets_streaks(self):
        link = FakeLink("e0", 0)
        link.routed = {"a", "b", "c"}
        router = StubRouter([link])
        scaler = make_scaler(router, min_engines=1, max_engines=3,
                             scale_up_at=2.0, scale_down_at=0.5,
                             up_window=2)
        scaler.tick()  # over threshold: streak 1
        link.routed = {"a"}  # per = 1.0: dead band
        scaler.tick()
        assert scaler.describe()["up_streak"] == 0
        link.routed = {"a", "b", "c"}
        scaler.tick()  # streak restarts at 1: still no spawn
        assert router.attached == []

    def test_scale_down_drains_idlest_then_reaps(self):
        a, b = FakeLink("e0", 0), FakeLink("e1", 1)
        a.routed = {"j"}
        router = StubRouter([a, b])
        scaler = make_scaler(router, min_engines=1, max_engines=2,
                             scale_down_at=0.6, down_window=2,
                             cooldown_s=0.0)
        scaler.tick()  # per = 0.5: streak 1
        assert router.drained == []
        scaler.tick()  # streak 2: drain the idle NEWEST engine
        assert router.drained == ["e1"]
        assert scaler.describe()["reaping"] == ["e1"]
        # Reap lands once the drained engine is empty.
        scaler.tick()
        assert router.detached == ["e1"]
        assert scaler.describe()["reaping"] == []

    def test_min_floor_respawns_immediately(self):
        router = StubRouter([])
        scaler = make_scaler(router, min_engines=1, max_engines=2,
                             cooldown_s=0.0)
        scaler.tick()  # below min: no window needed
        assert router.attached == ["spawn1"]

    def test_quarantined_engine_drained_and_replaced(self):
        a, b = FakeLink("e0", 0), FakeLink("e1", 1)
        a.health = "quarantined"
        router = StubRouter([a, b])
        scaler = make_scaler(router, min_engines=2, max_engines=3,
                             cooldown_s=0.0)
        scaler.tick()
        # Quarantine pass drains the broken engine; the min floor
        # respawns the lost capacity.
        assert router.drained == ["e0"]
        assert router.attached == ["spawn1"]
        # Once empty it reaps.
        scaler.tick()
        assert "e0" in router.detached

    def test_last_capacity_quarantine_spawns_before_drain(self):
        """Draining the LAST placeable engine would strand its
        migrating jobs on 'no live engine': the replacement spawns
        first, the drain waits for the next tick."""
        a = FakeLink("e0", 0)
        a.health = "quarantined"
        a.routed = {"j"}
        router = StubRouter([a])
        scaler = make_scaler(router, min_engines=1, max_engines=2,
                             cooldown_s=0.0)
        scaler.tick()
        assert router.attached == ["spawn1"]
        assert router.drained == []
        scaler.tick()  # somewhere to migrate now exists: drain
        assert router.drained == ["e0"]

    def test_spawn_fault_backs_off_and_retries(self):
        router = StubRouter([])
        scaler = make_scaler(router, min_engines=1, max_engines=2,
                             cooldown_s=0.0)
        before = int(
            telemetry.counter("fleet.spawn_failures").value
        )
        with faults.armed("engine.spawn:nth=1"):
            scaler.tick()  # injected spawn failure
            assert router.attached == []
            assert int(
                telemetry.counter("fleet.spawn_failures").value
            ) == before + 1
            scaler.tick()  # cooldown 0: the retry succeeds
            assert router.attached == ["spawn1"]

    def test_failed_attach_reaps_the_spawned_process(self):
        """A spawned-but-unattachable engine must not leak: the
        scale-up failure path terminates the process it started."""

        class FakeProc:
            def __init__(self):
                self.terminated = False
                self.waited = False

            def terminate(self):
                self.terminated = True

            def wait(self, timeout=None):
                self.waited = True

        proc = FakeProc()
        router = StubRouter([])

        def bad_attach(endpoint, engine_id, *, proc=None, timeout=180.0):
            raise FleetError("engine never listened")

        router.attach = bad_attach
        scaler = Autoscaler(
            router,
            lambda: ("fake://x", "x", proc),
            AutoscaleConfig(min_engines=1, max_engines=2,
                            cooldown_s=0.0, interval_s=0),
        )
        scaler.tick()  # min floor tries to spawn; attach fails
        assert proc.terminated and proc.waited
        assert router.attached == []

    def test_max_engines_is_a_ceiling(self):
        link = FakeLink("e0", 0)
        link.routed = {"a", "b", "c", "d"}
        router = StubRouter([link])
        scaler = make_scaler(router, min_engines=1, max_engines=1,
                             scale_up_at=2.0, up_window=1,
                             cooldown_s=0.0)
        scaler.tick()
        scaler.tick()
        assert router.attached == []

    def test_config_validates(self):
        with pytest.raises(ValueError):
            AutoscaleConfig(min_engines=2, max_engines=1)
        with pytest.raises(ValueError):
            AutoscaleConfig(scale_up_at=1.0, scale_down_at=1.0)
        with pytest.raises(ValueError):
            AutoscaleConfig(min_engines=0)


# ---------------------------------------------------------------------------
# Fleet fault seams (PERF.md §27 satellites)
# ---------------------------------------------------------------------------


class TestFleetFaultSeams:
    def test_router_place_fault_fails_submit_typed_and_retryable(self):
        router, (link,) = make_router()
        try:
            with faults.armed("router.place:nth=1"):
                with pytest.raises(faults.FaultInjected):
                    router.submit({"id": "f1", "digest_list": []})
                # Typed-and-bounded: no residue, the id retries fine.
                assert "f1" not in router._jobs
                ack = router.submit({"id": "f1", "digest_list": []})
                assert ack["engine"] == "e0"
        finally:
            router.close(shutdown_engines=False)

    def test_router_place_fault_on_requeue_quarantines_job(self):
        """A place fault during crash-replay fails the job WITH its
        checkpoint attached (the §23 quarantine-token discipline) —
        never silently, never crashing the requeue worker."""
        router, (link,) = make_router(replay_budget=1)
        try:
            events, emit = collector()
            router.submit({"id": "r1", "digest_list": []}, emit=emit)
            ckdoc = state_to_doc(CheckpointState(
                fingerprint="fp", cursor=SweepCursor(1, 5),
                n_emitted=9, n_hits=0, hits=[], wall_s=0.2,
            ))
            with faults.armed("router.place:nth=1"):
                router._on_job_event(link, {
                    "id": "r1", "event": "failed", "error": "boom",
                    "checkpoint": ckdoc,
                })
                wait_for(lambda: router.job("r1").state == "failed",
                         what="quarantined")
            (failed,) = [e for e in events
                         if e.get("event") == "failed"]
            assert failed["checkpoint"] == ckdoc
            # The worker survives: later submits still place.
            router.submit({"id": "after", "digest_list": []})
            assert router.job("after").link is link
        finally:
            router.close(shutdown_engines=False)

    def test_link_send_fault_fails_op_typed(self):
        import socket as socket_mod

        a, b = socket_mod.socketpair(socket_mod.AF_UNIX)
        link = EngineLink(a, "pair://", "e0")
        try:
            with faults.armed("link.send:nth=1"):
                with pytest.raises(FleetError) as exc:
                    link.request({"op": "stats"}, timeout=5.0)
                assert "send failed" in str(exc.value)
        finally:
            link.close()
            b.close()


# ---------------------------------------------------------------------------
# Stats surface
# ---------------------------------------------------------------------------


class TestElasticStats:
    def test_fleet_section_carries_elastic_signals(self):
        router, (link,) = make_router(
            engine_capacity=2, max_pending=8, shed_policy="oldest"
        )
        try:
            scaler = make_scaler(
                StubRouter(), min_engines=1, max_engines=4
            )
            router.autoscaler = scaler
            fleet = router.stats()["fleet"]
            assert fleet["jobs_pending"] == 0
            assert fleet["max_pending"] == 8
            assert fleet["engine_capacity"] == 2
            assert fleet["shed_policy"] == "oldest"
            assert fleet["engines"][0]["health"] == "healthy"
            assert fleet["autoscale"]["min"] == 1
            assert fleet["autoscale"]["max"] == 4
            for key in ("jobs_rejected", "jobs_shed",
                        "scrape_retries", "engines_quarantined",
                        "engines_detached"):
                assert fleet[key] == 0
        finally:
            router.close(shutdown_engines=False)


# ---------------------------------------------------------------------------
# Spawned multi-process elastic tier (slow): forced scale smoke + the
# chaos soak
# ---------------------------------------------------------------------------


def _spawn_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("A5GEN_FAULTS", None)
    return env


def _elastic_fleet(tmp_path, *, n0=1, engine_capacity=2, max_pending=32,
                   **cfg_kw):
    eng_dir = str(tmp_path / "engines")
    eng_args = ["--lanes", "64", "--blocks", "16", "--superstep", "1",
                "--schema-cache", str(tmp_path / "cache")]
    env = _spawn_env()
    router = FleetRouter(poll_s=0.5, defaults=cfg(),
                         engine_capacity=engine_capacity,
                         max_pending=max_pending)
    specs = spawn_engines(n0, eng_dir, engine_args=eng_args, env=env)
    for sock_path, eid, proc in specs:
        router.attach(sock_path, eid, proc=proc, timeout=300)
    counter = [n0]

    def spawner():
        idx = counter[0]
        counter[0] += 1
        (spec,) = spawn_engines(1, eng_dir, engine_args=eng_args,
                                start_index=idx, env=env)
        return spec

    cfg_kw.setdefault("interval_s", 0)
    scaler = Autoscaler(router, spawner, AutoscaleConfig(**cfg_kw))
    return router, scaler


@pytest.mark.slow
class TestElasticSpawned:
    def test_forced_scale_up_then_down_with_parity(self, tmp_path):
        """The CI elastic smoke: a 1-engine fleet under a 3-tenant
        burst (capacity 1) must scale up to its max of 2, finish every
        tenant byte-identically to solo, then drain + reap back to the
        min — spawn and reap both through the REAL process path."""
        router, scaler = _elastic_fleet(
            tmp_path, n0=1, engine_capacity=1,
            min_engines=1, max_engines=2,
            scale_up_at=1.5, scale_down_at=0.5,
            up_window=1, down_window=1, cooldown_s=0.0,
        )
        try:
            jobs = {}
            for i in range(3):
                digs = planted_digests(BIG_WORDS, (i, -1),
                                       decoys=30 + i)
                col = _Collector()
                jobs[f"j{i}"] = (digs, col)
                router.submit(job_doc(f"j{i}", BIG_WORDS, digs),
                              emit=col)
            assert router.pending_depth() == 2
            scaler.tick()  # backlog 3 over 1 engine: spawn
            wait_for(lambda: len(router.engines()) == 2,
                     timeout=300, what="scale-up")
            assert router.stats()["fleet"]["autoscale"]["scale_ups"] \
                == 1
            deadline = time.monotonic() + 600
            for jid in jobs:
                assert router.wait(
                    jid, timeout=max(1.0, deadline - time.monotonic())
                ), jid
                assert router.job(jid).state == "done", jid
            for jid, (digs, col) in jobs.items():
                _res, want = solo_hits(BIG_WORDS, digs)
                assert event_hits(col.events) == want, jid
            # Idle now: the scaler drains + reaps back to min.
            scaler.tick()  # down streak 1 -> drain (down_window=1)
            wait_for(
                lambda: (scaler.tick() or
                         len(router.engines()) == 1),
                timeout=120, what="scale-down reap",
            )
            fleet = router.stats()["fleet"]
            assert fleet["autoscale"]["scale_downs"] == 1
            assert fleet["engines_detached"] == 1
            # The reaped engine's process actually exited.
            assert all(
                l.proc is None or l.proc.poll() is None
                for l in router.engines()
            )
        finally:
            router.close(shutdown_engines=True)

    def test_elastic_chaos_soak_seeded_kills_byte_parity(self,
                                                         tmp_path):
        """The §27 top-tier contract: M churning tenants while a
        seeded schedule SIGKILLs engines and the autoscaler scales
        through it — every tenant finishes with byte-exact hit parity
        vs solo, the pending queue stays bounded, and the fleet ends
        with capacity again."""
        soak_words = WORDS * 40
        router, scaler = _elastic_fleet(
            tmp_path, n0=2, engine_capacity=2, max_pending=32,
            min_engines=1, max_engines=3,
            scale_up_at=1.5, scale_down_at=0.25,
            up_window=1, down_window=8, cooldown_s=1.0,
        )
        max_seen_pending = [0]
        stop_sampling = threading.Event()

        def sample():
            while not stop_sampling.wait(0.05):
                max_seen_pending[0] = max(
                    max_seen_pending[0], router.pending_depth()
                )

        threading.Thread(target=sample, daemon=True).start()
        ticker_stop = threading.Event()

        def ticker():
            while not ticker_stop.wait(0.5):
                scaler.tick()

        threading.Thread(target=ticker, daemon=True).start()
        try:
            jobs = {}
            for i in range(4):
                digs = planted_digests(soak_words, (i, 5 + i, -1),
                                       decoys=40 + i)
                col = _Collector()
                jobs[f"t{i}"] = (digs, col)
                router.submit(job_doc(f"t{i}", soak_words, digs),
                              emit=col)
            # Seeded kill schedule: SIGKILL the engine carrying t0
            # once it streams, then (if more than one engine lives)
            # the one carrying t2.
            assert jobs["t0"][1].first_hit.wait(300)
            victim = router.job("t0").link
            if victim is not None and victim.proc is not None:
                os.kill(victim.proc.pid, signal.SIGKILL)
            assert jobs["t2"][1].first_hit.wait(300)
            live = [l for l in router.engines()
                    if l.alive and l.proc is not None]
            second = router.job("t2").link
            if second is not None and second.proc is not None \
                    and len(live) > 1 and second.alive:
                os.kill(second.proc.pid, signal.SIGKILL)
            for jid, (digs, col) in jobs.items():
                assert router.wait(jid, timeout=900), jid
                assert router.job(jid).state == "done", (
                    jid, router.job(jid).state, col.events[-2:]
                )
            for jid, (digs, col) in jobs.items():
                res, want = solo_hits(soak_words, digs)
                assert event_hits(col.events) == want, jid
                (done,) = [e for e in col.events
                           if e.get("event") == "done"]
                assert done["n_hits"] == res.n_hits
            fleet = router.stats()["fleet"]
            assert fleet["engine_deaths"] >= 1
            assert fleet["jobs_replayed"] >= 1
            # Bounded-queue pin: the soak never outgrew max_pending.
            assert max_seen_pending[0] <= 32
            assert router.pending_depth() == 0
            # The fleet self-healed: at least one live engine serves.
            assert any(l.alive for l in router.engines())
        finally:
            ticker_stop.set()
            stop_sampling.set()
            router.close(shutdown_engines=True)

"""Pallas MD5 kernel: interpret-mode CPU parity against the XLA path and
hashlib (SURVEY.md §7 step 4; PERF.md §3). The kernel itself targets TPU;
``interpret=True`` runs the same program through the Pallas interpreter so
word-exactness is pinned without hardware."""

import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.ops.hashes import digest_bytes, md5
from hashcat_a5_table_generator_tpu.ops.pallas_md5 import (
    _ROWS_PER_TILE,
    md5_pallas,
    pallas_supported,
)

N = 128 * _ROWS_PER_TILE  # one grid tile


def _random_batch(width, seed=0):
    rng = np.random.default_rng(seed)
    msg = rng.integers(0, 256, size=(N, width), dtype=np.uint8)
    length = rng.integers(0, width + 1, size=(N,)).astype(np.int32)
    # Zero the padding region like the expansion kernels do.
    msg = np.where(np.arange(width)[None, :] < length[:, None], msg, 0)
    return jnp.asarray(msg), jnp.asarray(length)


@pytest.mark.parametrize("width", [4, 24, 52])
def test_interpret_matches_xla_path(width):
    msg, length = _random_batch(width, seed=width)
    got = np.asarray(md5_pallas(msg, length, interpret=True))
    want = np.asarray(md5(msg, length))
    np.testing.assert_array_equal(got, want)


def test_interpret_matches_hashlib():
    msg, length = _random_batch(24, seed=7)
    got = np.asarray(
        digest_bytes(md5_pallas(msg, length, interpret=True), "md5")
    )
    msg_np, len_np = np.asarray(msg), np.asarray(length)
    for i in range(0, N, 997):  # sample lanes
        want = hashlib.md5(bytes(msg_np[i, : len_np[i]])).digest()
        assert bytes(got[i]) == want, i


def test_ineligible_geometry_falls_back():
    # Width needing two MD5 blocks and a non-tile-multiple lane count both
    # route through the XLA path transparently.
    for n, width in [(N, 64), (100, 24)]:
        rng = np.random.default_rng(1)
        msg = jnp.asarray(
            rng.integers(97, 123, size=(n, width), dtype=np.uint8)
        )
        length = jnp.full((n,), min(width, 30), dtype=jnp.int32)
        assert not pallas_supported(n, width)
        got = np.asarray(md5_pallas(msg, length, interpret=True))
        want = np.asarray(md5(msg, length))
        np.testing.assert_array_equal(got, want)

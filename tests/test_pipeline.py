"""Fused attack pipeline: crack step, candidates step, host hit decode, and
the shard_map'd step on the 8-virtual-device CPU mesh."""

import hashlib

import jax
import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec,
    block_arrays,
    build_plan,
    decode_variant,
    digest_arrays,
    lane_cursor,
    make_candidates_step,
    make_crack_step,
    plan_arrays,
    table_arrays,
    unpack_bits,
)
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.parallel.mesh import (
    make_device_blocks,
    make_mesh,
    make_sharded_crack_step,
    replicate,
    shard_leading,
    stack_blocks,
)
from hashcat_a5_table_generator_tpu.tables.compile import compile_table

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a"]


def _oracle_candidates(spec: AttackSpec, word: bytes, sub_map):
    return list(
        iter_candidates(
            word,
            sub_map,
            spec.min_substitute,
            spec.max_substitute,
            substitute_all=spec.mode.startswith("suball"),
            reverse=spec.mode in ("reverse", "suball-reverse"),
            bug_compat=False,
        )
    )


def _run_crack(spec, sub_map, words, targets, lanes=2048):
    ct = compile_table(sub_map)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(targets, spec.algo)
    step = make_crack_step(spec, num_lanes=lanes, out_width=plan.out_width)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)

    hits = []
    total_emitted = 0
    w, rank = 0, 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank, max_variants=lanes
        )
        if batch.total == 0:
            break
        out = step(p, t, block_arrays(batch), d)
        total_emitted += int(out["n_emitted"])
        lanes_hit = np.nonzero(unpack_bits(out["hit_bits"], lanes))[0]
        for word_row, vrank in lane_cursor(plan, batch, lanes_hit):
            hits.append(decode_variant(plan, ct, spec, word_row, vrank))
        assert int(out["n_hits"]) == len(lanes_hit)
    return hits, total_emitted, plan


class TestCrackStep:
    @pytest.mark.parametrize(
        "mode", ["default", "reverse", "suball", "suball-reverse"]
    )
    def test_planted_hits_found(self, mode):
        spec = AttackSpec(mode=mode, algo="md5")
        # Plant digests of two oracle candidates + decoys.
        oracle = _oracle_candidates(spec, b"password", LEET)
        planted = sorted({oracle[0], oracle[-1]})
        targets = [hashlib.md5(c).digest() for c in planted]
        targets += [hashlib.md5(b"decoy%d" % i).digest() for i in range(100)]
        hits, emitted, _ = _run_crack(spec, LEET, WORDS, targets)
        assert sorted(set(hits)) == planted
        # Emitted count == total oracle candidates over all words.
        want_total = sum(
            len(_oracle_candidates(spec, w, LEET)) for w in WORDS
        )
        assert emitted == want_total

    def test_no_targets_no_hits(self):
        spec = AttackSpec(mode="default", algo="md5")
        hits, emitted, _ = _run_crack(spec, LEET, WORDS, [])
        assert hits == []
        assert emitted > 0

    def test_sha1_and_ntlm(self):
        for algo in ("sha1", "ntlm"):
            spec = AttackSpec(mode="suball", algo=algo)
            cand = _oracle_candidates(spec, b"sesame", LEET)[1]
            if algo == "sha1":
                target = hashlib.sha1(cand).digest()
            else:
                from tests.test_hashes import _ref_md4

                target = _ref_md4(
                    bytes(sum(([b, 0] for b in cand), []))
                )
            hits, _, _ = _run_crack(spec, LEET, WORDS, [target])
            assert cand in hits

    def test_min_window_respected(self):
        spec = AttackSpec(mode="default", algo="md5", min_substitute=2)
        oracle = [
            c
            for w in WORDS
            for c in _oracle_candidates(spec, w, LEET)
        ]
        _, emitted, _ = _run_crack(spec, LEET, WORDS, [])
        assert emitted == len(oracle)


class TestCandidatesStep:
    def test_multiset_matches_oracle(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        packed = pack_words(WORDS)
        plan = build_plan(spec, ct, packed)
        step = make_candidates_step(
            spec, num_lanes=2048, out_width=plan.out_width
        )
        p, t = plan_arrays(plan), table_arrays(ct)
        from collections import Counter

        got = Counter()
        w, rank = 0, 0
        while True:
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=2048
            )
            if batch.total == 0:
                break
            cand, clen, wrow, emit = step(p, t, block_arrays(batch))
            cand, clen, emit = map(np.asarray, (cand, clen, emit))
            for i in np.nonzero(emit)[0]:
                got[bytes(cand[i, : clen[i]])] += 1
        from collections import Counter as C

        want = C()
        for w_ in WORDS:
            want.update(_oracle_candidates(spec, w_, LEET))
        assert got == want


class TestShardedStep:
    def test_eight_device_mesh_matches_single(self):
        assert len(jax.devices()) >= 8, "conftest must provide 8 CPU devices"
        spec = AttackSpec(mode="suball", algo="md5")
        ct = compile_table(LEET)
        packed = pack_words(WORDS)
        plan = build_plan(spec, ct, packed)
        oracle = _oracle_candidates(spec, b"octopus", LEET)
        targets = [hashlib.md5(oracle[0]).digest()]
        ds = build_digest_set(targets, "md5")

        mesh = make_mesh(8)
        lanes = 64  # small budget -> multiple launches, uneven tails
        step = make_sharded_crack_step(
            spec, mesh, lanes_per_device=lanes, out_width=plan.out_width
        )
        p, t, d = replicate(
            mesh, (plan_arrays(plan), table_arrays(ct), digest_arrays(ds))
        )

        hits = []
        emitted = 0
        w, rank = 0, 0
        while True:
            batches, w, rank = make_device_blocks(
                plan, n_devices=8, lanes_per_device=lanes,
                start_word=w, start_rank=rank,
            )
            if sum(b.total for b in batches) == 0:
                break
            blocks = shard_leading(mesh, stack_blocks(batches))
            out = step(p, t, d, blocks)
            emitted += int(out["n_emitted"])
            hit = unpack_bits(out["hit_bits"], 8 * lanes)
            for dev in range(8):
                dev_lanes = np.nonzero(hit[dev * lanes : (dev + 1) * lanes])[0]
                for word_row, vrank in lane_cursor(
                    plan, batches[dev], dev_lanes
                ):
                    hits.append(
                        decode_variant(plan, ct, spec, word_row, vrank)
                    )

        want_total = sum(len(_oracle_candidates(spec, w_, LEET)) for w_ in WORDS)
        assert emitted == want_total
        assert hits == [oracle[0]]

    def test_stack_blocks_padding(self):
        spec = AttackSpec(mode="default", algo="md5")
        ct = compile_table(LEET)
        packed = pack_words([b"a"])  # tiny space: later devices get nothing
        plan = build_plan(spec, ct, packed)
        batches, _, _ = make_device_blocks(
            plan, n_devices=4, lanes_per_device=8
        )
        blocks = stack_blocks(batches)
        nb = len(blocks["count"]) // 4
        assert all(
            blocks["count"][i * nb :].sum() == 0 for i in range(1, 4)
        )


def test_static_block_padding_avoids_retraces():
    # With max_blocks + num_blocks padding, every launch presents identical
    # input shapes, so the jitted step compiles exactly once.
    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(LEET)
    packed = pack_words(WORDS)
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set([], "md5")
    nb, lanes = 8, 64
    step = make_crack_step(spec, num_lanes=lanes, out_width=plan.out_width)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    w, rank, launches = 0, 0, 0
    while True:
        batch, w, rank = make_blocks(
            plan, start_word=w, start_rank=rank,
            max_variants=lanes, max_blocks=nb,
        )
        if batch.total == 0:
            break
        step(p, t, block_arrays(batch, num_blocks=nb), d)
        launches += 1
    assert launches > 1
    assert step._cache_size() == 1


def test_pad_batch_empty_preserves_slot_width():
    # Regression: an empty batch (sweep exhausted / all-fallback tail) must
    # pad to the plan's slot width, not collapse to width 1 — otherwise the
    # jitted step retraces and the expand kernel's slot indexing breaks.
    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(LEET)
    plan = build_plan(spec, ct, pack_words(WORDS))
    empty, w, rank = make_blocks(
        plan, start_word=plan.batch, start_rank=0, max_variants=64
    )
    assert empty.total == 0
    from hashcat_a5_table_generator_tpu.ops.blocks import pad_batch

    padded = pad_batch(empty, 4)
    assert padded.base_digits.shape == (4, plan.num_slots)
    assert padded.count.sum() == 0


def test_spec_validation():
    with pytest.raises(ValueError):
        AttackSpec(mode="bogus")
    with pytest.raises(ValueError):
        AttackSpec(algo="crc32")
    assert AttackSpec(mode="default", min_substitute=0).effective_min == 1
    assert AttackSpec(mode="reverse", min_substitute=0).effective_min == 0
